"""Speculative decoding: draft-and-verify multi-token serving cycles.

Every plain engine step emits exactly ONE token per decode row, so decode
throughput is bounded by per-step launch + memory-bandwidth cost no matter
how cheap the model is. This module adds the draft-and-verify path on top
of the continuous-batching engine with NO new kernels:

1. DRAFT — a cheap source proposes k tokens per decode row:
   - `SelfDraft`: early-exit self-speculation. The draft pass runs only
     the first `num_layers` of the SAME stack/theta (then the full
     final_ln + logits head) via `TransformerLm.PagedStepPrefix`. Draft
     steps thread the engine states as a TRANSIENT copy — drafted KV/SSM
     writes are discarded, the verify step re-writes every kept position.
   - `ModelDraft`: an independent tiny draft model — pure O(1)-state
     (SSM) stacks only, so draft rows cost ZERO KV pages (the SSD-duality
     trade: flat [slots, N, H, S] state instead of paged KV). Its
     recurrent state advances ONLY over committed tokens: each cycle a
     ragged catch-up pass consumes the tokens committed since last cycle
     (<= k+1 wide in steady state), then k-1 transient proposal steps run
     whose state mutations are discarded — so draft rejection needs no
     rollback machinery at all.

2. VERIFY — the scheduler builds ONE ragged [B, k+1] step (the exact
   mixed-step machinery: `BlockPrefill` already IS "k+1 causal queries
   against a paged prefix"): each row carries [t0, d_1..d_k] at
   in_len = row_k + 1; opted-out rows ride along with in_len == 1, which
   is bitwise the legacy decode step for them.

3. ACCEPT/ROLLBACK — `core/sampling.SpecVerifyTokens` picks the accepted
   prefix (greedy match, or residual speculative sampling at
   temperature > 0, composing with the per-request seeded streams).
   Rolling back the rejected tail is free for KV pages (the write cursor
   is host-side and reads never pass q_pos + in_len — the scheduler just
   doesn't advance `seq.pos`); O(1)-state mixers instead return their
   per-column state trajectory (`ssm_col_states`) and `_SelectAcceptedCols`
   restores each slot to the last accepted column on device, inside the
   same compiled verify program.

Step-program cost: in the engine's default ragged mode the verify lane is
FOLDED INTO the one unified step program (spec rows are simply rows of
width k+1 on the packed token axis, and SpecVerifyTokens runs on their
gathered logits inside the same jit), so speculation adds only the draft
program(s). In legacy mode the verify step is a THIRD compiled step
program ([B, k+1]) next to decode and mixed. Either way,
admission/eviction still only rewrite int32 block tables.

TREE speculation (w > 1 on either draft source, ragged mode only): the
draft proposes a token TREE per row — w branches forked at depth 1, each
a chain of k tokens, packed branch-major so draft index bi * k + d is
branch bi's depth-(d+1) node. Branch heads are the top-w tokens of the
root distribution at temperature 0 and w i.i.d. draws from it otherwise
(i.i.d. siblings are what keeps the verify's multi-round residual
rejection exactly target-distributed); each branch then continues as an
ordinary chain draft under a branch-folded key. Draft-phase KV/state
writes stay TRANSIENT — branches sequentially overwrite each other's
scratch slots, which can only cost acceptance rate, never correctness,
because the unified step re-writes every tree slot at full depth and
core/sampling.SpecVerifyTree guarantees the emitted stream. ModelDraft
checkpoints its recurrent state after the committed catch-up and replays
every branch from that checkpoint.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import sampling
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.serving import scheduler as scheduler_lib

# key salt separating the draft model's sampling streams from the target's
# acceptance/bonus streams (both are per-request replayable)
_DRAFT_KEY_SALT = 0x5BEC


# -- stack census (shared with serving/engine.py) -----------------------------


def MixerLayers(task):
  """[(mixer_layer, multiplicity)] over the whole stack.

  Handles all four stack shapes: plain Stacked (x_layers), plain
  Repeated (body = one TransformerLayer, xN), and the hybrid Repeated
  whose body is itself a StackedTransformerLayers block (body.x_layers,
  each xN)."""
  stack = task.stack
  body = getattr(stack, "body", None)
  if body is not None:
    reps = stack.p.num_layers
    inner = body.x_layers if hasattr(body, "x_layers") else [body]
    return [(l.self_atten.atten, reps) for l in inner]
  return [(l.self_atten.atten, 1) for l in stack.x_layers]


def MixerCensus(task) -> dict:
  """Counts attention vs O(1)-state mixers; prices the per-slot state.

  A mixer is 'O(1)-state' iff it exposes StateBytesPerSlot (the
  core/ssm.py contract); everything else is a paged-KV attention layer.
  """
  num_attention = num_ssm = state_bytes = 0
  for mixer, reps in MixerLayers(task):
    if hasattr(mixer, "StateBytesPerSlot"):
      num_ssm += reps
      state_bytes += reps * mixer.StateBytesPerSlot()
    else:
      num_attention += reps
  return {
      "num_attention": num_attention,
      "num_ssm": num_ssm,
      "decode_state_bytes_per_slot": state_bytes,
  }


# -- draft-source configs -----------------------------------------------------


class SelfDraft:
  """Early-exit self-speculation: first `num_layers` of the target stack.

  k: draft depth proposed per decode row per cycle (chain verify width
  k+1; tree verify width 1 + w*k). w: draft-tree width — 1 (default)
  keeps the exact linear-chain draft, w > 1 forks w branches at depth 1.
  num_layers: flat trunk depth of the draft pass (must divide the scanned
  repeat-body depth for RepeatedTransformerLayer stacks)."""

  def __init__(self, k: int = 4, num_layers: int = 1, w: int = 1):
    assert k >= 1 and num_layers >= 1 and w >= 1, (k, num_layers, w)
    self.k = int(k)
    self.w = int(w)
    self.num_layers = int(num_layers)

  def Describe(self) -> dict:
    return {"draft": "self", "k": self.k, "w": self.w,
            "num_layers": self.num_layers}


class ModelDraft:
  """Independent tiny draft model (pure O(1)-state stack, pageless).

  w: draft-tree width — 1 (default) keeps the exact linear-chain draft,
  w > 1 forks w branches at depth 1, each replayed from the recurrent
  state checkpointed after the committed catch-up."""

  def __init__(self, task, theta, k: int = 4, w: int = 1):
    assert k >= 1 and w >= 1, (k, w)
    self.k = int(k)
    self.w = int(w)
    self.task = task
    self.theta = theta

  def Describe(self) -> dict:
    return {"draft": "model", "k": self.k, "w": self.w,
            "num_layers": self.task.p.num_layers}


# -- device-side helpers ------------------------------------------------------


def _SelectAcceptedCols(states, accept_len):
  """Rolls every collected SSM trajectory back to the accepted column.

  Walks the states pytree; wherever a node carries `col_states`
  [..., slots, C, N, H, S] (core/ssm.py spec-verify mode), replaces
  `state` with the column at accept_len (the state AFTER processing the
  last committed verify input) and strips the trajectory so the returned
  pytree matches the engine's steady-state structure."""
  idx = accept_len.astype(jnp.int32)

  def _Walk(node):
    if isinstance(node, NestedMap):
      if "col_states" in node:
        cols = node["col_states"]
        shape = (1,) * (cols.ndim - 5) + (idx.shape[0], 1, 1, 1, 1)
        sel = jnp.take_along_axis(cols, idx.reshape(shape), axis=-4)
        out = NestedMap({k: v for k, v in node.items()
                         if k != "col_states"})
        out.state = jnp.squeeze(sel, axis=-4)
        return out
      return NestedMap({k: _Walk(v) for k, v in node.items()})
    if isinstance(node, list):
      return [_Walk(v) for v in node]
    if isinstance(node, tuple):
      return tuple(_Walk(v) for v in node)
    return node

  return _Walk(states)


# -- the runner ---------------------------------------------------------------


class SpecRunner:
  """Owns the draft + verify compiled programs and draft-model state.

  Built by ServingLoop when a draft source is configured; all scheduler
  bookkeeping stays in serving/scheduler.py, all device programs live
  here. Host-side it additionally tracks, for ModelDraft, each slot
  sequence's `draft_pos` (committed tokens the draft state has consumed).
  """

  def __init__(self, config, *, task, theta, max_batch: int,
               page_size: int, prefill_chunk: int, temperature: float,
               top_k: int, sample_seed: int, compile_log=None):
    self.config = config
    self.k = config.k
    self.w = getattr(config, "w", 1)
    # optional observe.CompileLog: routes the verify program through a
    # one-shot AOT compile so the engine's compile records cover all
    # three step programs (decode / mixed / spec_verify)
    self._compile_log = compile_log
    self.is_self = isinstance(config, SelfDraft)
    self._task = task
    self._temperature = float(temperature)
    self._top_k = int(top_k)
    self._sample_seed = int(sample_seed)
    self._max_batch = max_batch
    self._prefill_chunk = prefill_chunk
    self._has_ssm = MixerCensus(task)["num_ssm"] > 0
    # accepted-length histogram: hist[m] = verify rows whose accepted
    # draft prefix (tree: accepted root-to-leaf DEPTH along the winning
    # branch) had length m — each such row committed m + 1 tokens
    self.accepted_len_hist = np.zeros((self.k + 1,), np.int64)

    if self.is_self:
      depth = task.p.num_layers
      assert config.num_layers <= depth, (config.num_layers, depth)
      body = getattr(task.stack, "body", None)
      if body is not None:
        # repeat stack: the early-exit prefix slices whole scanned repeats,
        # so the draft depth must cover an integral number of them — fail
        # here rather than as a shape assert inside the first spec cycle
        body_depth = len(body.x_layers) if hasattr(body, "x_layers") else 1
        assert config.num_layers % body_depth == 0, (
            f"SelfDraft num_layers={config.num_layers} must be a multiple "
            f"of the scanned repeat body depth ({body_depth}) for this "
            "target stack")
      self.draft_task = None
      self.draft_theta = None
      self.draft_states = None
    else:
      census = MixerCensus(config.task)
      assert census["num_attention"] == 0, (
          "ModelDraft requires a pageless draft (pure O(1)-state mixer "
          f"stack); draft has {census['num_attention']} attention layers "
          "— a paged draft would need its own page pool")
      assert config.task.p.vocab_size == task.p.vocab_size, (
          config.task.p.vocab_size, task.p.vocab_size)
      self.draft_task = config.task
      self.draft_theta = config.theta
      init_fn = jax.jit(config.task.InitPagedDecodeState,
                        static_argnums=(1, 2, 3, 4))
      # pageless: the pool geometry is ignored, only num_slots matters
      self.draft_states = init_fn(config.theta, 2, page_size, max_batch,
                                  None)
    self._BuildPrograms()

  # -- compiled programs -----------------------------------------------------

  def _BuildPrograms(self):
    k, temp, topk = self.k, self._temperature, self._top_k
    task, has_ssm = self._task, self._has_ssm
    base_key = self._sample_seed

    def _Verify(theta, states, ids, q_pos, in_len, tables, seeds, pos,
                q_logits):
      logits, new_states = task.PagedStep(theta, ids, states, tables,
                                          q_pos, in_len,
                                          ssm_col_states=has_ssm)
      draft_valid = (jnp.arange(k, dtype=jnp.int32)[None]
                     < (in_len - 1)[:, None])
      key = jax.random.PRNGKey(base_key)
      out, alen = sampling.SpecVerifyTokens(
          logits, ids[:, 1:], q_logits, key, temperature=temp, top_k=topk,
          row_seeds=seeds, row_pos=pos, draft_valid=draft_valid)
      if has_ssm:
        new_states = _SelectAcceptedCols(new_states, alen)
      return out, alen, new_states

    self._verify_fn = jax.jit(_Verify)

    def _DraftKey():
      return jax.random.fold_in(jax.random.PRNGKey(base_key),
                                _DRAFT_KEY_SALT)

    w = self.w

    def _BranchHeads(l0, key_d, seeds, pos0):
      # depth-1 sibling set from the shared root distribution l0: the
      # top-w distinct tokens at temperature 0 (maximum acceptance mass),
      # w i.i.d. branch-keyed draws otherwise — the i.i.d. sibling law
      # SpecVerifyTree's multi-round residual rejection is exact for
      if temp <= 0.0:
        return jax.lax.top_k(l0, w)[1].astype(jnp.int32)
      cols = []
      for bi in range(w):
        kb = key_d if bi == 0 else jax.random.fold_in(key_d, bi)
        cols.append(sampling.SampleFromLogits(
            l0, kb, temperature=temp, top_k=topk, row_seeds=seeds,
            positions=pos0))
      return jnp.stack(cols, 1)

    if self.is_self:
      num_layers = self.config.num_layers

      def _SelfPropose(theta, states, ids0, q_pos, act, tables, seeds,
                       pos0):
        key_d = _DraftKey()
        st, cur = states, ids0
        d_toks, q_logits = [], []
        for j in range(k):
          logits, st = task.PagedStepPrefix(theta, cur, st, tables,
                                            q_pos + j, act, num_layers)
          lj = logits[:, 0]
          tok = sampling.SampleFromLogits(
              lj, key_d, temperature=temp, top_k=topk, row_seeds=seeds,
              positions=pos0 + j)
          d_toks.append(tok)
          q_logits.append(lj)
          cur = tok[:, None]
        # st (drafted KV writes through the prefix layers) is DISCARDED:
        # the verify step re-writes every kept position at full depth
        return jnp.stack(d_toks, 1), jnp.stack(q_logits, 1)

      def _SelfProposeTree(theta, states, ids0, q_pos, act, tables, seeds,
                           pos0):
        key_d = _DraftKey()
        # root step: the shared depth-1 distribution every branch head is
        # picked from (its KV write at q_pos is transient, like all draft
        # writes — the unified step re-writes every tree slot)
        logits0, st = task.PagedStepPrefix(theta, ids0, states, tables,
                                           q_pos, act, num_layers)
        l0 = logits0[:, 0]
        heads = _BranchHeads(l0, key_d, seeds, pos0)           # [B, w]
        d_toks = [None] * (w * k)
        q_logits = [None] * (w * k)
        for bi in range(w):
          kb = key_d if bi == 0 else jax.random.fold_in(key_d, bi)
          cur = heads[:, bi]
          d_toks[bi * k] = cur
          q_logits[bi * k] = l0
          # each branch continues as an ordinary chain draft over the
          # SAME scratch slots q_pos+1.. — later branches overwrite
          # earlier ones' transient KV, and each step only attends slots
          # <= its own position, so every branch sees exactly
          # prefix + root + its own prefix
          for d in range(1, k):
            logits, st = task.PagedStepPrefix(theta, cur[:, None], st,
                                              tables, q_pos + d, act,
                                              num_layers)
            lj = logits[:, 0]
            cur = sampling.SampleFromLogits(
                lj, kb, temperature=temp, top_k=topk, row_seeds=seeds,
                positions=pos0 + d)
            d_toks[bi * k + d] = cur
            q_logits[bi * k + d] = lj
        return jnp.stack(d_toks, 1), jnp.stack(q_logits, 1)

      self._self_draft_fn = jax.jit(_SelfPropose if w == 1
                                    else _SelfProposeTree)
    else:
      draft_task = self.draft_task

      def _Consume(theta_d, states_d, ids, q_pos, in_len):
        tables = jnp.zeros((ids.shape[0], 1), jnp.int32)  # pageless
        _, st = draft_task.PagedStep(theta_d, ids, states_d, tables,
                                     q_pos, in_len)
        return st

      self._consume_fn = jax.jit(_Consume)

      def _Propose(theta_d, states_d, catch_ids, dpos, clen, seeds, pos0):
        tables = jnp.zeros((catch_ids.shape[0], 1), jnp.int32)
        key_d = _DraftKey()
        # ragged catch-up over the tokens committed since last cycle;
        # this is the ONLY draft-state advance — proposals below are
        # transient, so draft rejection needs no rollback
        logits_c, st = draft_task.PagedStep(theta_d, catch_ids, states_d,
                                            tables, dpos, clen)
        last = jnp.clip(clen - 1, 0, k)[:, None, None]
        cur = jnp.take_along_axis(logits_c, last, axis=1)[:, 0]
        act = (clen > 0).astype(jnp.int32)
        st_t = st
        d_toks, q_logits = [], []
        for j in range(k):
          tok = sampling.SampleFromLogits(
              cur, key_d, temperature=temp, top_k=topk, row_seeds=seeds,
              positions=pos0 + j)
          d_toks.append(tok)
          q_logits.append(cur)
          if j < k - 1:
            lj, st_t = draft_task.PagedStep(
                theta_d, tok[:, None], st_t, tables,
                dpos + clen + j, act)
            cur = lj[:, 0]
        return jnp.stack(d_toks, 1), jnp.stack(q_logits, 1), st

      def _ProposeTree(theta_d, states_d, catch_ids, dpos, clen, seeds,
                       pos0):
        tables = jnp.zeros((catch_ids.shape[0], 1), jnp.int32)
        key_d = _DraftKey()
        # committed catch-up advances the KEPT draft state st; every
        # branch below replays from that checkpoint transiently
        logits_c, st = draft_task.PagedStep(theta_d, catch_ids, states_d,
                                            tables, dpos, clen)
        last = jnp.clip(clen - 1, 0, k)[:, None, None]
        l0 = jnp.take_along_axis(logits_c, last, axis=1)[:, 0]
        act = (clen > 0).astype(jnp.int32)
        heads = _BranchHeads(l0, key_d, seeds, pos0)           # [B, w]
        d_toks = [None] * (w * k)
        q_logits = [None] * (w * k)
        for bi in range(w):
          kb = key_d if bi == 0 else jax.random.fold_in(key_d, bi)
          st_t = st
          cur_tok = heads[:, bi]
          cur = l0
          for d in range(k):
            d_toks[bi * k + d] = cur_tok
            q_logits[bi * k + d] = cur
            if d < k - 1:
              lj, st_t = draft_task.PagedStep(
                  theta_d, cur_tok[:, None], st_t, tables,
                  dpos + clen + d, act)
              cur = lj[:, 0]
              cur_tok = sampling.SampleFromLogits(
                  cur, kb, temperature=temp, top_k=topk, row_seeds=seeds,
                  positions=pos0 + d + 1)
        return jnp.stack(d_toks, 1), jnp.stack(q_logits, 1), st

      self._propose_fn = jax.jit(_Propose if w == 1 else _ProposeTree)

  # -- host-side draft-state bookkeeping (ModelDraft) ------------------------

  @staticmethod
  def _StreamToken(seq, idx: int) -> int:
    """Committed token idx of a sequence (prompt then generated)."""
    pl = len(seq.req.prompt)
    return seq.req.prompt[idx] if idx < pl else seq.out[idx - pl]

  def _DrainBacklog(self, rows, row_k):
    """Catches the draft state up when a row's backlog outgrew the k+1
    catch-up window — most commonly the row just finished prompt prefill
    (the draft state never consumes the prompt on the wire; it replays
    the committed stream host-side, which also covers prefix-cache
    admissions whose prefill skipped cached tokens entirely). Runs the
    consume program in prefill_chunk-wide bites before the row's first
    draft; steady state never enters the loop. This replaced the legacy
    mixed-step ConsumeStep ride-along, whose prefill-row masking special
    case existed only because the old engine gave prefill its own step
    shape — under the unified ragged step there is no separate mixed
    step to ride."""
    cp = self._prefill_chunk
    while True:
      todo = []
      for i, seq in enumerate(rows):
        if (seq is None or seq.state is not scheduler_lib.SeqState.DECODE
            or row_k[i] == 0):
          continue
        backlog = seq.pos + 1 - seq.draft_pos
        excess = backlog - (self.k + 1)
        if excess > 0:
          todo.append((i, seq, min(excess, cp)))
      if not todo:
        return
      b = len(rows)
      ids = np.zeros((b, cp), np.int32)
      q_pos = np.zeros((b,), np.int32)
      in_len = np.zeros((b,), np.int32)
      for i, seq, n in todo:
        for j in range(n):
          ids[i, j] = self._StreamToken(seq, seq.draft_pos + j)
        q_pos[i] = seq.draft_pos
        in_len[i] = n
      self.draft_states = self._consume_fn(
          self.draft_theta, self.draft_states, jnp.asarray(ids),
          jnp.asarray(q_pos), jnp.asarray(in_len))
      for i, seq, n in todo:
        seq.draft_pos += n

  def _BuildCatchup(self, rows, row_k):
    b, kp1 = len(rows), self.k + 1
    ids = np.zeros((b, kp1), np.int32)
    dpos = np.zeros((b,), np.int32)
    clen = np.zeros((b,), np.int32)
    for i, seq in enumerate(rows):
      if (seq is None or seq.state is not scheduler_lib.SeqState.DECODE
          or row_k[i] == 0):
        continue
      n = seq.pos + 1 - seq.draft_pos
      assert 1 <= n <= kp1, (n, kp1)
      for j in range(n):
        ids[i, j] = self._StreamToken(seq, seq.draft_pos + j)
      dpos[i] = seq.draft_pos
      clen[i] = n
    return ids, dpos, clen

  # -- per-cycle entry points ------------------------------------------------

  def Draft(self, theta, states, vbatch, tables):
    """Proposes k tokens per spec row; returns (np [B, k], device q_logits).

    ModelDraft: also advances the committed draft state (catch-up) and
    each row's draft_pos."""
    if self.is_self:
      act = (vbatch.in_len > 0).astype(np.int32)
      d, q = self._self_draft_fn(
          theta, states, jnp.asarray(vbatch.ids[:, :1]),
          jnp.asarray(vbatch.q_pos), jnp.asarray(act), jnp.asarray(tables),
          jnp.asarray(vbatch.row_seeds), jnp.asarray(vbatch.row_pos))
      return np.asarray(d), q
    self._DrainBacklog(vbatch.rows, vbatch.row_k)
    ids, dpos, clen = self._BuildCatchup(vbatch.rows, vbatch.row_k)
    d, q, self.draft_states = self._propose_fn(
        self.draft_theta, self.draft_states, jnp.asarray(ids),
        jnp.asarray(dpos), jnp.asarray(clen),
        jnp.asarray(vbatch.row_seeds), jnp.asarray(vbatch.row_pos))
    for i, seq in enumerate(vbatch.rows):
      if clen[i]:
        seq.draft_pos += int(clen[i])
    return np.asarray(d), q

  def Verify(self, theta, states, ids: np.ndarray, vbatch, tables,
             q_logits):
    """The third compiled step program: ragged [B, k+1] verify + accept +
    SSM rollback in ONE jit. Returns (out_tokens, accept_len, states)."""
    args = (theta, states, jnp.asarray(ids), jnp.asarray(vbatch.q_pos),
            jnp.asarray(vbatch.in_len), jnp.asarray(tables),
            jnp.asarray(vbatch.row_seeds), jnp.asarray(vbatch.row_pos),
            q_logits)
    if self._compile_log is not None:
      return self._compile_log.Call("spec_verify", self._verify_fn, *args)
    return self._verify_fn(*args)

  def Describe(self) -> dict:
    return self.config.Describe()
