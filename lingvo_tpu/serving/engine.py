"""ServingLoop: the continuous-batching serving engine driver.

Glues the three layers below into a running service:

    ops/block_decode.py      the ragged paged attention kernels
    serving/kv_cache.py      host-side page ownership
    serving/scheduler.py     admission / step building / retirement

Device-side there are exactly TWO compiled programs, both shape-static:
the pure decode step (`[B, 1]` token per live row) and the mixed step
(`[B, prefill_chunk]`, prefilling rows consume prompt chunks while decode
rows ride along with in_len == 1). Admission and eviction only rewrite
int32 block tables between calls, so sequences enter and leave mid-flight
with zero recompilation — the property that lets short requests overtake
long ones instead of idling behind them (the batch-synchronous
`GShardDecode` failure mode this engine replaces).

Greedy sampling only: the ISSUE's parity bar is token-identity with
batch-synchronous `GShardDecode` at temperature 0, and argmax keeps the
step program deterministic with no per-request RNG state to shuffle
through slots.

Two front doors:
- async: `Start()` + `Submit(prompt, max_new) -> StreamHandle` — tokens
  stream out per request as they are committed; `Cancel()` mid-flight.
- sync: `RunBatch(prompts, prompt_lens)` — GShardDecode-parity mode:
  submit everything, drive the loop inline, return `[B, max_new]` outputs
  in submission order.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import scheduler as scheduler_lib

_END = object()   # stream sentinel


class StreamHandle:
  """Per-request streaming output + lifecycle handle."""

  def __init__(self, req_id, engine, submit_time: float):
    self.id = req_id
    self._engine = engine
    self._q = queue.Queue()
    self._tokens = []
    self._done = threading.Event()
    self.finish_reason: Optional[str] = None
    self.submit_time = submit_time
    self.first_token_time: Optional[float] = None
    self.finish_time: Optional[float] = None

  # engine-side
  def _Push(self, token: int):
    if self.first_token_time is None:
      self.first_token_time = time.perf_counter()
    self._tokens.append(token)
    self._q.put(token)

  def _Finish(self, reason: str):
    self.finish_reason = reason
    self.finish_time = time.perf_counter()
    self._done.set()
    self._q.put(_END)

  # user-side
  def Tokens(self, timeout: Optional[float] = None):
    """Yields tokens as they are generated; returns on completion."""
    while True:
      item = self._q.get(timeout=timeout)
      if item is _END:
        return
      yield item

  def Result(self, timeout: Optional[float] = None) -> list:
    """Blocks until the request finishes; returns all generated tokens."""
    if not self._done.wait(timeout=timeout):
      raise TimeoutError(f"request {self.id!r} still running")
    return list(self._tokens)

  def Cancel(self) -> bool:
    return self._engine.Cancel(self.id)

  @property
  def done(self) -> bool:
    return self._done.is_set()


class ServingLoop:
  """Continuous-batching decode service over a block-table page pool."""

  def __init__(self, task, theta, *, page_size: int, num_pages: int,
               max_batch: int, max_seq_len: int, prefill_chunk: int = 8,
               default_max_new: int = 32, eos_id: Optional[int] = None):
    """task: a TransformerLm-style task exposing InitPagedDecodeState /
    PagedStep. num_pages: allocator-owned pages (the device pool gets one
    extra trash page). max_seq_len: static per-sequence capacity bound
    (block-table width = ceil(max_seq_len / page_size)).
    """
    assert page_size >= 1 and num_pages >= 1 and max_batch >= 1
    assert max_seq_len >= page_size
    self._task = task
    self._theta = theta
    self.page_size = page_size
    self.num_pages = num_pages
    self.max_batch = max_batch
    self.prefill_chunk = prefill_chunk
    self.default_max_new = default_max_new
    self.eos_id = eos_id
    self.alloc = kv_cache.PageAllocator(num_pages, page_size)
    table_pages = self.alloc.PagesFor(max_seq_len)
    self.sched = scheduler_lib.Scheduler(
        max_batch, self.alloc, table_pages, prefill_chunk)
    # pool page num_pages (the +1) is the trash page padding writes hit
    init_fn = jax.jit(task.InitPagedDecodeState, static_argnums=(1, 2))
    self._states = init_fn(theta, num_pages + 1, page_size)
    # donate the pool into each step off-cpu (XLA:CPU can't alias + warns)
    donate = (1,) if jax.default_backend() != "cpu" else ()

    def _Step(theta, states, ids, q_pos, in_len, tables):
      logits, states = task.PagedStep(theta, ids, states, tables, q_pos,
                                      in_len)
      return jnp.argmax(logits, axis=-1).astype(jnp.int32), states

    self._step_fn = jax.jit(_Step, donate_argnums=donate)
    # silent-fallback visibility: classify ONCE which attention path the
    # compiled step will take, and count ineligible (dense-fallback) steps
    self.paged_path = self._ClassifyPath()
    self._handles: dict = {}
    self._counters = {
        "steps": 0, "decode_steps": 0, "mixed_steps": 0,
        "tokens_emitted": 0, "prompt_tokens": 0,
        "dense_fallback_steps": 0,
    }
    self._lock = threading.RLock()
    self._work = threading.Condition(self._lock)
    self._thread: Optional[threading.Thread] = None
    self._running = False
    self._seq_counter = 0

  # -- path classification ---------------------------------------------------

  def _FindAtten(self):
    stack = self._task.stack
    layer = getattr(stack, "body", None)
    if layer is None:
      layer = stack.x_layers[0]
    return layer.self_atten.atten

  def _ClassifyPath(self) -> str:
    """'pallas' | 'xla' | 'dense' — what PagedStep actually lowers to.

    A dense fallback (ineligible attention config) is CORRECT but not
    paged-fast; it must be visible, never silent (ISSUE satellite)."""
    atten = self._FindAtten()
    if not atten.BlockDecodeEligible(self.page_size):
      return "dense"
    return "pallas" if jax.default_backend() == "tpu" else "xla"

  # -- async API -------------------------------------------------------------

  def Start(self):
    with self._lock:
      if self._running:
        return self
      self._running = True
      self._thread = threading.Thread(target=self._Loop, daemon=True,
                                      name="serving-loop")
      self._thread.start()
    return self

  def Stop(self, drain: bool = True, timeout: float = 60.0):
    """drain=True finishes in-flight + queued work first."""
    with self._lock:
      if not self._running:
        return
      if not drain:
        for h in list(self._handles.values()):
          if not h.done:
            self.Cancel(h.id)   # RLock: reentrant under self._lock
      self._work.notify_all()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      with self._lock:
        if not self.sched.HasWork():
          self._running = False
          self._work.notify_all()
          break
      time.sleep(0.005)
    else:
      with self._lock:
        self._running = False
        self._work.notify_all()
    if self._thread is not None:
      self._thread.join(timeout=timeout)
      self._thread = None

  def Submit(self, prompt, max_new_tokens: Optional[int] = None,
             eos_id=_END) -> StreamHandle:
    """Queues a request; returns its streaming handle immediately."""
    max_new = max_new_tokens or self.default_max_new
    eos = self.eos_id if eos_id is _END else eos_id
    with self._lock:
      self._seq_counter += 1
      req_id = self._seq_counter
      req = scheduler_lib.Request(req_id, prompt, max_new, eos)
      total = len(req.prompt) + req.max_new
      if self.alloc.PagesFor(total) > self.alloc.num_pages:
        raise ValueError(
            f"request needs {self.alloc.PagesFor(total)} pages; the pool "
            f"only has {self.alloc.num_pages} — it could never be admitted")
      self.sched.Submit(req)
      handle = StreamHandle(req_id, self, time.perf_counter())
      self._handles[req_id] = handle
      self._work.notify_all()
    return handle

  def Cancel(self, req_id) -> bool:
    with self._lock:
      ok = self.sched.Cancel(req_id)
      if ok:
        h = self._handles.get(req_id)
        if h is not None and not h.done:
          h._Finish("cancelled")
      return ok

  def _Loop(self):
    while True:
      with self._lock:
        if not self._running:
          return
        if not self.sched.HasWork():
          self._work.wait(timeout=0.05)
          continue
      self.StepOnce()

  # -- core step (shared by sync and async modes) ----------------------------

  def StepOnce(self) -> int:
    """One admit → device step → commit iteration; returns #events."""
    with self._lock:
      self.sched.EvictCancelled()
      self.sched.Admit()
      batch = self.sched.BuildStep()
      if batch is None:
        return 0
      tables = np.array(self.sched.block_tables)  # freeze under the lock
    sampled, new_states = self._step_fn(
        self._theta, self._states, jnp.asarray(batch.ids),
        jnp.asarray(batch.q_pos), jnp.asarray(batch.in_len),
        jnp.asarray(tables))
    self._states = new_states
    sampled = np.asarray(sampled)
    with self._lock:
      events = self.sched.CommitStep(batch, sampled)
      self._counters["steps"] += 1
      self._counters["mixed_steps" if batch.mixed else "decode_steps"] += 1
      self._counters["prompt_tokens"] += batch.prompt_tokens
      if self.paged_path == "dense":
        self._counters["dense_fallback_steps"] += 1
      for req_id, tok, finished in events:
        self._counters["tokens_emitted"] += 1
        h = self._handles.get(req_id)
        if h is None:
          continue
        h._Push(tok)
        if finished:
          h._Finish(self.sched._by_id[req_id].finish_reason)
    return len(events)

  # -- sync GShardDecode-parity mode ----------------------------------------

  def RunBatch(self, prompts: np.ndarray, prompt_lens: np.ndarray,
               max_new_tokens: Optional[int] = None) -> np.ndarray:
    """Decodes a fixed prompt set inline; returns [B, max_new] int32.

    The continuous-batching twin of `GShardDecode.DecodeOnce`: same greedy
    sampling, token-identical outputs (asserted in tests), but sequences
    retire individually so the pool drains as rows finish. eos is ignored
    here (GShardDecode always decodes exactly max_decode_steps tokens)."""
    assert self._thread is None, "RunBatch drives the loop inline; Stop() first"
    prompts = np.asarray(prompts)
    max_new = max_new_tokens or self.default_max_new
    handles = []
    for i in range(prompts.shape[0]):
      ln = int(prompt_lens[i])
      handles.append(self.Submit(prompts[i, :ln], max_new, eos_id=None))
    while True:
      with self._lock:
        if not self.sched.HasWork():
          break
      self.StepOnce()
    out = np.zeros((prompts.shape[0], max_new), np.int32)
    for i, h in enumerate(handles):
      toks = h.Result(timeout=0)
      out[i, :len(toks)] = toks
    return out

  # -- introspection ---------------------------------------------------------

  def Stats(self) -> dict:
    with self._lock:
      stats = dict(self._counters)
      stats["paged_path"] = self.paged_path
      stats["scheduler"] = self.sched.Stats()
      stats["kv_pages"] = self.alloc.Stats()
    return stats
