"""ServingLoop: the continuous-batching serving engine driver.

Glues the four layers below into a running service:

    ops/ragged_block_attend.py   the packed-token paged attention kernel
    ops/block_decode.py          the legacy-shape paged attention kernels
    serving/kv_cache.py          host-side page ownership
    serving/scheduler.py         admission / step building / retirement

Device-side there is ONE compiled step program (step_mode='ragged', the
default): every serving iteration packs its work onto a single static
[T] token axis (core/ragged.py) — a plain decode row contributes 1
token, a prefilling row a token-budgeted prompt chunk, a speculating row
its feedback token plus k draft tokens — and dispatches the same
program. The legacy engine needed a separate compiled shape per step
kind (pure decode `[B, 1]`, mixed `[B, prefill_chunk]`, spec-verify
`[B, k+1]`), which cost extra compiles, forced whole-batch padding to
the widest row, and serialized speculation behind prefill; the packed
axis removes all three. Admission and eviction only rewrite int32 block
tables between calls, so sequences enter and leave mid-flight with zero
recompilation — the property that lets short requests overtake long
ones instead of idling behind them (the batch-synchronous `GShardDecode`
failure mode this engine replaces). `step_mode='legacy'` keeps the old
two-to-three-program engine as the comparison baseline; its byte-exact
equivalence to ragged mode at temperature 0 is asserted in tests.

Speculative decoding (serving/spec_decode.py) configures a draft source
(`spec=SelfDraft(...)` or `spec=ModelDraft(...)`): each iteration where
at least one decode row speculates runs a draft pass proposing k tokens
per such row, then the SAME unified step verifies them — spec rows are
just width-(k+1) rows whose gathered logits flow through
`SpecVerifyTokens` inside the one program — and commits each row's
accepted prefix plus a bonus/correction token, rolling write cursors
back over rejected tails. Prefilling neighbors ride the same step, so
spec cycles no longer wait for pure-decode iterations. At temperature 0
the output streams are token-identical to the non-spec engine (greedy
acceptance keeps exactly the argmax prefix); at temperature > 0
residual speculative sampling preserves each request's seeded output
distribution. Per-request `spec_k` on Submit() opts individual requests
out (0) or caps their draft length.

Sampling: temperature 0 (default) is pure argmax — token-identical to
batch-synchronous `GShardDecode`, the parity bar asserted in tests. With
temperature > 0 (optional top_k) each request samples from its OWN
stream (core/sampling.py): the draw for output position t of a request
with seed s is a pure function of (engine sample_seed, s, t), carried
through the scheduler as per-row `row_seeds`/`row_pos`, so continuations
are replayable no matter which slot or batch neighbors the scheduler
picked.

O(1)-state mixers (core/ssm.py): stacks whose mixers carry fixed-size
recurrent state instead of KV pages plug in unchanged — their PagedStep
state is a [max_batch, ...] per-slot array reset device-side on each
sequence's first chunk (q_pos == 0). The engine takes a mixer census at
construction: hybrid stacks price both resources, and pure-SSM stacks
set `needs_kv_pages=False` so admission is bounded by decode slots only
(the allocator is never charged — the more-concurrent-requests-at-fixed-
HBM property the ISSUE's bench demonstrates).

Two front doors:
- async: `Start()` + `Submit(prompt, max_new) -> StreamHandle` — tokens
  stream out per request as they are committed; `Cancel()` mid-flight.
- sync: `RunBatch(prompts, prompt_lens)` — GShardDecode-parity mode:
  submit everything, drive the loop inline, return `[B, max_new]` outputs
  in submission order.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import observe
from lingvo_tpu.core import ragged as ragged_lib
from lingvo_tpu.core import sampling
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.quant import kv as kv_quant
from lingvo_tpu.quant import weights as quant_weights
from lingvo_tpu.serving import kv_cache
from lingvo_tpu.serving import prefix_cache as prefix_cache_lib
from lingvo_tpu.serving import scheduler as scheduler_lib
from lingvo_tpu.serving import spec_decode

_END = object()   # stream sentinel


class StreamHandle:
  """Per-request streaming output + lifecycle handle."""

  def __init__(self, req_id, engine, submit_time: float):
    self.id = req_id
    self._engine = engine
    self._q = queue.Queue()
    self._tokens = []
    self._done = threading.Event()
    self.finish_reason: Optional[str] = None
    self.submit_time = submit_time
    self.admit_time: Optional[float] = None
    self.first_token_time: Optional[float] = None
    self.finish_time: Optional[float] = None

  # engine-side
  def _Push(self, token: int):
    if self.first_token_time is None:
      self.first_token_time = time.perf_counter()
    self._tokens.append(token)
    self._q.put(token)

  def _Finish(self, reason: str):
    self.finish_reason = reason
    self.finish_time = time.perf_counter()
    self._done.set()
    self._q.put(_END)

  # user-side
  def Tokens(self, timeout: Optional[float] = None):
    """Yields tokens as they are generated; returns on completion."""
    while True:
      item = self._q.get(timeout=timeout)
      if item is _END:
        return
      yield item

  def Result(self, timeout: Optional[float] = None) -> list:
    """Blocks until the request finishes; returns all generated tokens."""
    if not self._done.wait(timeout=timeout):
      raise TimeoutError(f"request {self.id!r} still running")
    return list(self._tokens)

  def Cancel(self) -> bool:
    return self._engine.Cancel(self.id)

  @property
  def done(self) -> bool:
    return self._done.is_set()


class ServingLoop:
  """Continuous-batching decode service over a block-table page pool."""

  def __init__(self, task, theta, *, page_size: int, num_pages: int,
               max_batch: int, max_seq_len: int, prefill_chunk: int = 8,
               default_max_new: int = 32, eos_id: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               sample_seed: int = 0, kv_cache_dtype: Optional[str] = None,
               serve_int8_weights: bool = False, spec=None,
               prefix_cache=None, trace=True, metrics_registry=None,
               serve_port: Optional[int] = None, watchdog=None,
               step_mode: str = "ragged",
               prefill_token_budget: Optional[int] = None,
               prefix_swap_persist: bool = False,
               scheduler_mode: str = "fifo",
               tenant_quotas=None, tenant_weights=None):
    """task: a TransformerLm-style task exposing InitPagedDecodeState /
    PagedStep. num_pages: allocator-owned pages (the device pool gets one
    extra trash page). max_seq_len: static per-sequence capacity bound
    (block-table width = ceil(max_seq_len / page_size)).
    temperature/top_k/sample_seed: sampling controls (module docstring);
    temperature <= 0 compiles to the pre-sampling argmax program.
    kv_cache_dtype: overrides the task's layer-level kv_cache_dtype for
    this engine's page pool (None keeps it; see quant/kv.py) — 'int8'
    turns on quantize-on-write KV pages with scale sidecars.
    serve_int8_weights: rewrite the served theta so decode projections run
    as `Int8Einsum` integer matmuls (quant/weights.py); the float theta is
    untouched, only this engine's copy is rewritten.
    spec: optional speculative-decoding draft source —
    `spec_decode.SelfDraft` (early-exit over the same theta) or
    `spec_decode.ModelDraft` (independent pageless draft model). None
    keeps the exact two-program legacy engine.
    prefix_cache: cross-request KV prefix sharing
    (serving/prefix_cache.py) — None (default) keeps the bit-exact
    legacy admission path, True builds a fresh PrefixCache over this
    engine's pool, or pass a PrefixCache instance (rebound via Bind —
    a cache built against a different pool or kv dtype is invalidated,
    never cross-shared). Requires an attention-only stack: O(1)-state
    mixers carry recurrent state the cache can neither share nor skip.
    trace: per-request lifecycle tracing (observe/trace.py) — True (the
    default; overhead is bounded by the bench's observability section)
    builds a fresh TraceRecorder, False disables, or pass a TraceRecorder
    to share/configure one. metrics_registry: the observe.MetricsRegistry
    this engine publishes through (None = a fresh per-engine registry, so
    replicas and tests stay isolated).
    serve_port: opt-in fleet endpoints (observe/export.py) — an integer
    starts a StatusServer on that port (0 = ephemeral, read
    `self.status_server.port`) serving /metrics, /statusz, /traces and
    /healthz over this engine's registry/Stats()/trace; the server stops
    with Stop(). watchdog: stall watchdog (observe/watchdog.py) — True
    builds a default StallWatchdog on this engine's registry, or pass a
    configured StallWatchdog (capture logdir, injectable clock); the
    engine heartbeats it per step and feeds it queue observations, and
    /healthz runs its Check() at scrape time.
    step_mode: 'ragged' (default) serves every iteration through ONE
    compiled packed-token program (core/ragged.py) — prefill chunks,
    plain decode rows, and spec-verify rows share each step; 'legacy'
    keeps the previous two-to-three-program engine (the byte-identity
    and bench baseline this PR's tests compare against).
    prefill_token_budget: ragged mode only — prompt tokens the packed
    step reserves beyond the worst-case decode tokens (defaults to
    prefill_chunk); decode capacity left idle by empty slots flows to
    prefill on top of it.
    prefix_swap_persist: what UpdateTheta does to the prefix cache —
    False (default) drops the whole radix tree (Invalidate), True keeps
    the tree and marks every page stale (MarkStale): stale pages are
    never served, but one warm re-prefill per live prefix refreshes its
    nodes in place, so hit_tokens recover without a cold tree restart.
    Per-swap override via UpdateTheta(persist_prefix=...).
    scheduler_mode: 'fifo' (default, the bit-exact legacy admission
    path) or 'priority' — SLO classes, per-tenant quotas, weighted-fair
    admission, and preemption by KV page spill to a host tier
    (serving/scheduler.py module docstring). The engine supplies the
    device halves: jitted whole-page gather/scatter over every paged
    leaf (spilled KV round-trips bitwise, int8 scale sidecars ride
    along) and slot-row gather/scatter over every O(1)-mixer state
    leaf. tenant_quotas: {tenant: (rate, burst) | TokenBucket} token-
    rate quotas enforced at Submit (QuotaExceeded before a handle is
    created). tenant_weights: {tenant: weight} for weighted-fair
    admission within a priority class.
    """
    assert page_size >= 1 and num_pages >= 1 and max_batch >= 1
    assert max_seq_len >= page_size
    self._task = task
    self.serve_int8_weights = bool(serve_int8_weights)
    if serve_int8_weights:
      theta, _ = quant_weights.Int8ServingTheta(theta)
    self._theta = theta
    self.page_size = page_size
    self.num_pages = num_pages
    self.max_batch = max_batch
    self.prefill_chunk = prefill_chunk
    self.default_max_new = default_max_new
    self.eos_id = eos_id
    self.temperature = float(temperature)
    self.top_k = int(top_k)
    self.sample_seed = int(sample_seed)
    # KV census BEFORE allocating: the effective cache dtype prices a page
    kv_census = kv_quant.StackKvCensus(task, kv_cache_dtype) or {}
    self.kv_cache_dtype = kv_census.get("kv_cache_dtype")
    self.kv_bytes_per_token = kv_census.get("kv_bytes_per_token", 0)
    self._kv_quantized = self.kv_cache_dtype == "int8"
    self._kv_override = kv_cache_dtype
    self.alloc = kv_cache.PageAllocator(
        num_pages, page_size,
        page_bytes=page_size * self.kv_bytes_per_token)
    table_pages = self.alloc.PagesFor(max_seq_len)
    # mixer census: which resource(s) this stack's decode state occupies
    self.mixers = self._MixerCensus()
    self.state_pool = None
    if self.mixers["num_ssm"] > 0:
      self.state_pool = kv_cache.StateSlotPool(
          max_batch, self.mixers["decode_state_bytes_per_slot"])
    # global prefix cache: opt-in KV page sharing across requests. Gated
    # to attention-only stacks — an SSM/hybrid row's recurrent state must
    # replay EVERY prompt token, so skipping cached prefill would decode
    # against wrong state (and the state itself is per-slot, unshareable).
    self.prefix_cache = None
    if prefix_cache is not None and prefix_cache is not False:
      if self.mixers["num_attention"] == 0 or self.mixers["num_ssm"] > 0:
        raise ValueError(
            "prefix_cache requires an attention-only stack: O(1)-state "
            f"mixers (census {self.mixers}) carry recurrent state that "
            "cannot be shared across requests or skipped by cached prefill")
      self.prefix_cache = (
          prefix_cache if isinstance(prefix_cache, prefix_cache_lib.PrefixCache)
          else prefix_cache_lib.PrefixCache())
      self.prefix_cache.Bind(self.alloc, self.kv_cache_dtype)
    self.prefix_swap_persist = bool(prefix_swap_persist)
    self.sched = scheduler_lib.Scheduler(
        max_batch, self.alloc, table_pages, prefill_chunk,
        needs_kv_pages=self.mixers["num_attention"] > 0,
        state_pool=self.state_pool, prefix_cache=self.prefix_cache,
        scheduler_mode=scheduler_mode, tenant_quotas=tenant_quotas,
        tenant_weights=tenant_weights)
    self.scheduler_mode = scheduler_mode
    # device halves of preemption spill/restore (priority mode): whole-
    # page gather/scatter across the paged leaves, slot-row gather/
    # scatter across the O(1)-mixer state leaves. All four run under the
    # engine lock on the loop thread (Admit is only called from
    # _AdmitPhase), so mutating self._states here is safe.
    if scheduler_mode == "priority":
      if self.mixers["num_attention"] > 0:
        self.sched.spill_fn = self._SpillPages
        self.sched.restore_fn = self._RestorePages
      if self.state_pool is not None:
        self.sched.state_spill_fn = self._SpillStateRow
        self.sched.state_restore_fn = self._RestoreStateRow
    self._slot_io_fns = None   # lazy (gather, scatter) jits over slot leaves
    # pool page num_pages (the +1) is the trash page padding writes hit;
    # num_slots sizes the per-slot O(1) mixer states (attention ignores it);
    # the kv dtype override is a static string arg (hashable)
    init_fn = jax.jit(task.InitPagedDecodeState, static_argnums=(1, 2, 3, 4))
    self._states = init_fn(theta, num_pages + 1, page_size, max_batch,
                           kv_cache_dtype)
    # donate the pool into each step off-cpu (XLA:CPU can't alias + warns)
    donate = (1,) if jax.default_backend() != "cpu" else ()
    temp, topk = self.temperature, self.top_k
    base_key = self.sample_seed

    def _Step(theta, states, ids, q_pos, in_len, tables, seeds, pos):
      logits, states = task.PagedStep(theta, ids, states, tables, q_pos,
                                      in_len)
      # sample every chunk column with the row's (seed, output-position)
      # stream; CommitStep consumes exactly one column per row (col 0 for
      # decode rows, the last valid prompt column for finishing prefills),
      # so identical draws across columns are never double-consumed
      key = jax.random.PRNGKey(base_key)
      cols = [
          sampling.SampleFromLogits(logits[:, c], key, temperature=temp,
                                    top_k=topk, row_seeds=seeds,
                                    positions=pos)
          for c in range(logits.shape[1])
      ]
      return jnp.stack(cols, axis=1), states

    self._step_fn = jax.jit(_Step, donate_argnums=donate)
    # copy-on-write executor: one jitted page copy across every page-pool
    # leaf of the decode state (compiled once; src/dst are traced scalars)
    self._cow_fn = (self._BuildCowFn(task, theta, kv_cache_dtype)
                    if self.prefix_cache is not None else None)
    # fleet page handoff (AdoptPrefix): jitted page gather/scatter pair,
    # built lazily — most engines never donate or adopt a prefix
    self._page_io_fns = None
    # observability (observe/): per-engine metrics registry, per-request
    # lifecycle trace, and one-shot compile records for the step programs
    self.metrics = (metrics_registry if metrics_registry is not None
                    else observe.MetricsRegistry("serving"))
    self.trace = (trace if isinstance(trace, observe.TraceRecorder)
                  else (observe.TraceRecorder() if trace else None))
    self._compile_log = observe.CompileLog(
        registry=self.metrics, namespace="serving/compile", donate=donate)
    # speculative decoding: the runner owns the draft + verify programs
    # and (for ModelDraft) the draft model's recurrent state
    self.spec = None
    if spec is not None:
      self.spec = spec_decode.SpecRunner(
          spec, task=task, theta=theta, max_batch=max_batch,
          page_size=page_size, prefill_chunk=prefill_chunk,
          temperature=self.temperature, top_k=self.top_k,
          sample_seed=self.sample_seed, compile_log=self._compile_log)
    # unified ragged step geometry: T packed tokens cover every slot's
    # worst-case decode width (1 + draft k) plus a prefill token budget;
    # wmax is the widest single row the one compiled program admits
    if step_mode not in ("ragged", "legacy"):
      raise ValueError(
          "step_mode must be 'ragged' or 'legacy', got %r" % (step_mode,))
    if (self.spec is not None and self.spec.w > 1
        and step_mode == "legacy"):
      raise ValueError(
          "tree speculation (draft width > 1) requires step_mode='ragged' "
          "— the legacy verify step is chain-only")
    self.step_mode = step_mode
    self.prefill_token_budget = int(prefill_token_budget or prefill_chunk)
    # a speculating row is 1 root + w*k tree nodes wide (chain: w == 1)
    spec_width = ((1 + self.spec.w * self.spec.k)
                  if self.spec is not None else 1)
    self._ragged_t = max_batch * spec_width + self.prefill_token_budget
    self._ragged_wmax = max(spec_width, self.prefill_token_budget)
    # tree KV repair needs each paged leaf's (page, token-offset) axes;
    # chain engines never repair (accepted prefixes are already in place)
    self._kv_leaf_axes = None
    if (self.spec is not None and self.spec.w > 1
        and self.mixers["num_attention"] > 0):
      self._kv_leaf_axes = self._PagedLeafAxes(task, theta, kv_cache_dtype)
    self._ragged_fn = self._BuildRaggedFn(task, donate)
    self._zero_qlogits = None   # lazy [B, w*k, V] f32 (no-draft spec steps)
    # silent-fallback visibility: classify ONCE which attention path the
    # compiled step will take, and count ineligible (dense-fallback) steps
    self.paged_path = self._ClassifyPath()
    self._handles: dict = {}
    # counters live in the registry under serving/* (schema is the single
    # source of the key set); Stats() maps them back to the plain keys.
    # All Inc() calls happen under the engine lock, so Stats() — which
    # also holds it — reads a mutually-consistent set.
    self._counters = {
        k: self.metrics.Counter(f"serving/{k}")
        for k in observe_schema.ENGINE_COUNTER_KEYS}
    # engine configuration facts + live sub-surfaces. Section callbacks
    # deliberately read WITHOUT the engine lock (a registry snapshot
    # holding the registry lock must never wait on the engine lock —
    # lock-order inversion against the hot path's counter Incs); the
    # atomic consistent read is Stats().
    self.metrics.Gauge("serving/paged_path").Set(self.paged_path)
    self.metrics.Gauge("serving/kv_cache_dtype").Set(self.kv_cache_dtype)
    self.metrics.Gauge("serving/kv_bytes_per_token").Set(
        self.kv_bytes_per_token)
    self.metrics.Gauge("serving/serve_int8_weights").Set(
        self.serve_int8_weights)
    self.metrics.SectionFn("scheduler", self.sched.Stats)
    self.metrics.SectionFn("kv_pages", self.alloc.Stats)
    self.metrics.SectionFn(
        "prefix_cache",
        self.prefix_cache.Stats if self.prefix_cache is not None
        else observe_schema.DisabledPrefixCacheStats)
    if self.state_pool is not None:
      self.metrics.SectionFn("state_slots", self.state_pool.Stats)
    if self.trace is not None:
      self.metrics.SectionFn("trace", self.trace.Stats)
    self._h_queue_wait = self.metrics.Histogram("serving/queue_wait_s")
    self._h_ttft = self.metrics.Histogram("serving/ttft_s")
    self._h_tpot = self.metrics.Histogram("serving/tpot_s")
    self._h_queue_wait_cls: dict = {}   # SLO class -> queue-wait Histogram
    self._pages_of: dict = {}   # req_id -> pages granted at admission
    self._profile_window = None
    self._lock = threading.RLock()
    self._work = threading.Condition(self._lock)
    self._thread: Optional[threading.Thread] = None
    self._running = False
    self._seq_counter = 0
    self._adopt_counter = 0   # transient page-handoff allocation owners
    # stall watchdog: StepOnce heartbeats + queue observations feed it;
    # the /healthz scrape thread (or a test) runs Check() — liveness must
    # be evaluated on a thread a hung step loop can't take down
    self.watchdog = None
    if watchdog is not None and watchdog is not False:
      self.watchdog = (watchdog
                       if isinstance(watchdog, observe.StallWatchdog)
                       else observe.StallWatchdog(self.metrics))
    # fleet-facing endpoints, opt-in via serve_port (0 = ephemeral port)
    self.status_server = None
    if serve_port is not None:
      self.status_server = observe.StatusServer(
          serve_port, registry=self.metrics, name="serving",
          statusz_fn=self.Stats, trace=self.trace,
          watchdog=self.watchdog).Start()

  # -- path classification ---------------------------------------------------

  def _MixerLayers(self):
    """[(mixer_layer, multiplicity)] — see spec_decode.MixerLayers."""
    return spec_decode.MixerLayers(self._task)

  def _MixerCensus(self) -> dict:
    """Attention vs O(1)-state census — see spec_decode.MixerCensus."""
    return spec_decode.MixerCensus(self._task)

  def _ClassifyPath(self) -> str:
    """'pallas[-int8]' | 'xla[-int8]' | 'dense' | 'ssm' — what PagedStep
    lowers to.

    A dense fallback (ineligible attention config) is CORRECT but not
    paged-fast; it must be visible, never silent (ISSUE satellite). With
    an int8 pool the fallback still reads quantized pages (gather +
    dequantize), but loses the in-kernel dequant — equally worth
    surfacing. 'ssm' = no attention layer at all: the page pool is never
    read and classification is about the recurrent-state path instead."""
    attens = [m for m, _ in self._MixerLayers()
              if not hasattr(m, "StateBytesPerSlot")]
    if not attens:
      return "ssm"
    if self._kv_quantized:
      if not all(a.QuantizedDecodeEligible(self.page_size) for a in attens):
        return "dense"
      suffix = "-int8"
    else:
      if not all(a.BlockDecodeEligible(self.page_size) for a in attens):
        return "dense"
      suffix = ""
    base = "pallas" if jax.default_backend() == "tpu" else "xla"
    return base + suffix

  # -- the unified ragged step program ---------------------------------------

  def _BuildRaggedFn(self, task, donate):
    """Jits THE serving step: packed-token forward + sampling (+ verify).

    One program covers every iteration shape the legacy engine needed
    two-to-three programs for: prefill chunks, plain decode rows, and
    spec-verify rows are just rows of different length on the same [T]
    token axis (core/ragged.py). Sampling is per TOKEN with each token
    broadcasting its row's (seed, output-position) stream — bitwise the
    legacy per-column draws, which sampled every chunk column with the
    same row stream. When a draft source is configured the verify lane
    is always computed (static structure): rows with row_k == 0 flow
    through SpecVerifyTokens as all-invalid and their column-0 output
    is exactly the plain draw, so no-draft steps run the SAME program
    with zero q_logits rather than a second compiled shape.

    Tree speculation (draft width w > 1) stays the SAME one program:
    speculating rows pack a w-ary token tree in DFS order, the verify
    lane rebuilds DFS-ordered target logits from the packed columns and
    runs SpecVerifyTree with a static branch table, the accepted path's
    K/V is gathered/scattered into the canonical chain slots inside the
    same jit (no second program, no host round-trip), and hybrid-SSM
    rows column-select the accepted LEAF's tree-scan trajectory. Width
    w == 1 engines compile the EXACT chain program below — chain
    speculation is the degenerate tree, bitwise.
    """
    temp, topk = self.temperature, self.top_k
    base_key = self.sample_seed
    b = self.max_batch
    spec_k = self.spec.k if self.spec is not None else 0
    spec_w = self.spec.w if self.spec is not None else 1
    collect = self.spec is not None and self.mixers["num_ssm"] > 0

    if spec_k == 0:
      def _RaggedStep(theta, states, tok_ids, rows, tables, seeds, pos):
        logits, new_states = task.RaggedStep(theta, tok_ids[None], states,
                                             tables, rows)
        logits = logits[0]                                     # [T, V]
        key = jax.random.PRNGKey(base_key)
        row = jnp.clip(rows.row_of, 0, b - 1)
        sampled = sampling.SampleFromLogits(
            logits, key, temperature=temp, top_k=topk,
            row_seeds=seeds[row], positions=pos[row])
        return sampled, new_states
    elif spec_w == 1:
      def _RaggedStep(theta, states, tok_ids, rows, tables, seeds, pos,
                      row_k, q_logits):
        logits, new_states = task.RaggedStep(theta, tok_ids[None], states,
                                             tables, rows,
                                             ssm_col_states=collect)
        logits = logits[0]                                     # [T, V]
        key = jax.random.PRNGKey(base_key)
        row = jnp.clip(rows.row_of, 0, b - 1)
        sampled = sampling.SampleFromLogits(
            logits, key, temperature=temp, top_k=topk,
            row_seeds=seeds[row], positions=pos[row])
        # verify lane: each row's first spec_k+1 token columns, gathered
        # back to [B, k+1] — prefill/no-draft rows gather garbage that
        # draft_valid masks out of acceptance entirely
        vcols = rows.row_cols[:, :spec_k + 1]
        v_logits = logits[vcols]
        d_toks = tok_ids[vcols[:, 1:]]
        draft_valid = (jnp.arange(spec_k, dtype=jnp.int32)[None]
                       < row_k[:, None])
        out, alen = sampling.SpecVerifyTokens(
            v_logits, d_toks, q_logits, key, temperature=temp, top_k=topk,
            row_seeds=seeds, row_pos=pos, draft_valid=draft_valid)
        if collect:
          # SSM trajectory restore: spec rows roll back to the accepted
          # column; every other row keeps the state after its LAST real
          # token (columns past row_len are identity steps, so the
          # clipped index is exact for 0-token rows too)
          restore = jnp.where(row_k > 0, alen,
                              jnp.clip(rows.row_len - 1, 0, None))
          new_states = spec_decode._SelectAcceptedCols(new_states, restore)
        return sampled, out, alen, new_states
    else:
      r = spec_w * spec_k
      ps = self.page_size
      trash_page = self.num_pages        # the pool's padding-write page
      kv_axes = self._kv_leaf_axes

      def _IdxTuple(ndim, pa, oa, pi, oi):
        idx = [slice(None)] * ndim
        idx[pa] = pi
        idx[oa] = oi
        return tuple(idx)

      def _RepairKv(states, tables, rows, row_k, alen, wbr):
        # Moves the accepted path's K/V (and int8 scale sidecars) from
        # its DFS tree slots to the canonical chain slots q_pos+1..
        # q_pos+m, so the committed cache is bit-identical to a chain
        # that decoded the same tokens. Branch-0 wins are pure identity
        # copies (src == dst); inactive (row, depth) pairs copy the
        # trash page onto itself so duplicate scatter indices can never
        # land on live pages.
        q_pos = rows.row_q_pos.astype(jnp.int32)
        dd = jnp.arange(1, spec_k + 1, dtype=jnp.int32)[None]    # [1, K]
        m = jnp.minimum(alen, row_k)[:, None]
        active = (row_k[:, None] > 0) & (dd <= m)
        src_slot = (q_pos[:, None] + 1
                    + wbr[:, None] * row_k[:, None] + dd - 1)
        dst_slot = q_pos[:, None] + dd
        cap = tables.shape[1] * ps
        src_slot = jnp.clip(src_slot, 0, cap - 1)
        dst_slot = jnp.clip(dst_slot, 0, cap - 1)
        bb = jnp.arange(b, dtype=jnp.int32)[:, None]
        sp = jnp.where(active, tables[bb, src_slot // ps], trash_page)
        so = jnp.where(active, src_slot % ps, 0)
        dp = jnp.where(active, tables[bb, dst_slot // ps], trash_page)
        do = jnp.where(active, dst_slot % ps, 0)
        leaves, treedef = jax.tree_util.tree_flatten(states)
        assert len(leaves) == len(kv_axes), (len(leaves), len(kv_axes))
        out = []
        for leaf, ax in zip(leaves, kv_axes):
          if ax is None:
            out.append(leaf)
            continue
          pa, oa = ax
          vals = leaf[_IdxTuple(leaf.ndim, pa, oa, sp, so)]
          out.append(
              leaf.at[_IdxTuple(leaf.ndim, pa, oa, dp, do)].set(vals))
        return jax.tree_util.tree_unflatten(treedef, out)

      def _RaggedStep(theta, states, tok_ids, rows, tables, seeds, pos,
                      row_k, row_w, q_logits):
        logits, new_states = task.RaggedStep(theta, tok_ids[None], states,
                                             tables, rows,
                                             ssm_col_states=collect)
        logits = logits[0]                                     # [T, V]
        key = jax.random.PRNGKey(base_key)
        row = jnp.clip(rows.row_of, 0, b - 1)
        sampled = sampling.SampleFromLogits(
            logits, key, temperature=temp, top_k=topk,
            row_seeds=seeds[row], positions=pos[row])
        # tree verify lane: draft node j = bi*k + d (the branch-major
        # draft layout) sits at packed column 1 + bi*row_k + d; rows
        # with clamped width/depth leave the tail invalid, so the
        # branch table stays a STATIC arange and per-row shape lives
        # entirely in draft_valid. DFS-ordered target logits are
        # rebuilt so node j's after-distribution is column j + 1 —
        # the SpecVerifyTree contract.
        j = jnp.arange(r, dtype=jnp.int32)
        bi_j, d_j = j // spec_k, j % spec_k
        nvalid = ((bi_j[None] < row_w[:, None])
                  & (d_j[None] < row_k[:, None]))              # [B, R]
        node_col = jnp.where(
            nvalid, 1 + bi_j[None] * row_k[:, None] + d_j[None], 0)
        ntok = jnp.take_along_axis(rows.row_cols, node_col, axis=1)
        v_logits = jnp.concatenate(
            [logits[rows.row_cols[:, :1]], logits[ntok]], axis=1)
        d_toks = tok_ids[ntok]
        branches = jnp.broadcast_to(
            jnp.arange(r, dtype=jnp.int32).reshape(1, spec_w, spec_k),
            (b, spec_w, spec_k))
        out, alen, wbr = sampling.SpecVerifyTree(
            v_logits, d_toks, branches, q_logits, key, temperature=temp,
            top_k=topk, row_seeds=seeds, row_pos=pos, draft_valid=nvalid)
        if collect:
          # SSM trajectory restore: the accepted LEAF's packed column —
          # the tree scan threaded states parent-to-child, so the leaf
          # column holds exactly the chain state after root + path
          leaf_col = jnp.where(alen > 0, 1 + wbr * row_k + (alen - 1), 0)
          restore = jnp.where(row_k > 0, leaf_col,
                              jnp.clip(rows.row_len - 1, 0, None))
          new_states = spec_decode._SelectAcceptedCols(new_states, restore)
        if kv_axes is not None:
          new_states = _RepairKv(new_states, tables, rows, row_k, alen,
                                 wbr)
        return sampled, out, alen, new_states

    return jax.jit(_RaggedStep, donate_argnums=donate)

  def _ZeroQLogits(self):
    """All-zero draft logits for spec-engine steps where no row drafted
    (still prefilling): the verify lane runs with draft_valid all-False,
    so the values are never consumed — they only pin the one compiled
    signature. Tree engines widen to the full w*k draft layout."""
    if self._zero_qlogits is None:
      self._zero_qlogits = jnp.zeros(
          (self.max_batch, self.spec.w * self.spec.k,
           self._task.p.vocab_size), jnp.float32)
    return self._zero_qlogits

  def _PagedLeafAxes(self, task, theta, kv_cache_dtype):
    """(page_axis, offset_axis) per decode-state leaf, None for unpaged.

    The same structural detection as _BuildCowFn, run along BOTH pool
    geometry parameters: the leaf axis that grows with the pool size is
    the page axis, the one that grows with page_size is the token-offset
    axis. Detecting the offset axis independently matters because int8
    scale sidecars keep it on a different axis ([P, N, page_size]) than
    the K/V pools ([P, page_size, N, H]) — adjacency can't be assumed."""
    def _Shapes(np_total, ps):
      return jax.eval_shape(
          lambda th: task.InitPagedDecodeState(
              th, np_total, ps, self.max_batch, kv_cache_dtype), theta)

    base = jax.tree_util.tree_leaves(
        _Shapes(self.num_pages + 1, self.page_size))
    bigger = jax.tree_util.tree_leaves(
        _Shapes(self.num_pages + 2, self.page_size))
    wider = jax.tree_util.tree_leaves(
        _Shapes(self.num_pages + 1, self.page_size + 1))
    axes = []
    for la, lb, lc in zip(base, bigger, wider):
      dp = [i for i, (x, y) in enumerate(zip(la.shape, lb.shape))
            if x != y]
      do = [i for i, (x, y) in enumerate(zip(la.shape, lc.shape))
            if x != y]
      assert len(dp) <= 1 and len(do) <= 1, (la.shape, lb.shape, lc.shape)
      assert bool(dp) == bool(do), (la.shape, dp, do)
      axes.append((dp[0], do[0]) if dp else None)
    return axes

  # -- prefix-cache support --------------------------------------------------

  def _BuildCowFn(self, task, theta, kv_cache_dtype):
    """Jits a whole-page device copy `states, src, dst -> states`.

    Which decode-state leaves are page pools (and which axis pages them)
    is detected STRUCTURALLY: abstract-eval InitPagedDecodeState at two
    pool sizes and diff the leaf shapes — the axis that grew is the page
    axis. That handles every layout uniformly: flat stacks page axis 0,
    repeat-stacked layers page axis 1 (leaves carry a leading reps axis),
    int8 K/V plus their f32 scale sidecars each get their own leaf, and
    O(1)-mixer state leaves (shape-independent of the pool) are left
    untouched."""
    def _Shapes(np_total):
      return jax.eval_shape(
          lambda th: task.InitPagedDecodeState(
              th, np_total, self.page_size, self.max_batch, kv_cache_dtype),
          theta)

    a = jax.tree_util.tree_leaves(_Shapes(self.num_pages + 1))
    b = jax.tree_util.tree_leaves(_Shapes(self.num_pages + 2))
    axes = []
    for la, lb in zip(a, b):
      diff = [i for i, (x, y) in enumerate(zip(la.shape, lb.shape))
              if x != y]
      assert len(diff) <= 1, (la.shape, lb.shape)
      axes.append(diff[0] if diff else None)

    def _CopyPage(states, src, dst):
      leaves, treedef = jax.tree_util.tree_flatten(states)
      assert len(leaves) == len(axes), (len(leaves), len(axes))
      out = []
      for leaf, ax in zip(leaves, axes):
        if ax is None:
          out.append(leaf)
        else:
          row = jnp.take(leaf, src, axis=ax)
          out.append(leaf.at[(slice(None),) * ax + (dst,)].set(row))
      return jax.tree_util.tree_unflatten(treedef, out)

    donate = (0,) if jax.default_backend() != "cpu" else ()
    return jax.jit(_CopyPage, donate_argnums=donate)

  def _RunCow(self, admitted):
    """Executes pending copy-on-write page splits for freshly admitted
    sequences (caller holds the lock; the loop thread owns _states)."""
    for seq in admitted:
      for src, dst in seq.cow_pairs:
        self._states = self._cow_fn(self._states,
                                    jnp.asarray(src, jnp.int32),
                                    jnp.asarray(dst, jnp.int32))
      seq.cow_pairs = []

  def _PageIoFns(self):
    """Jitted whole-page (gather, scatter) across the page-pool leaves —
    the device half of the fleet page handoff (serving/fleet.py):
    gather(states, idx) pulls the [n]-page blocks of one pool out as a
    flat leaf list, scatter(states, idx, blocks) lands them in another
    pool of the same stack. Which leaves are paged (and on which axis)
    reuses the _PagedLeafAxes structural detection, so int8 K/V scale
    sidecars are just more paged leaves and ride along."""
    if self._page_io_fns is None:
      axes = [ax[0] if ax is not None else None
              for ax in self._PagedLeafAxes(self._task, self._theta,
                                            self._kv_override)]

      def _Gather(states, idx):
        leaves = jax.tree_util.tree_leaves(states)
        assert len(leaves) == len(axes), (len(leaves), len(axes))
        return [jnp.take(leaf, idx, axis=ax)
                for leaf, ax in zip(leaves, axes) if ax is not None]

      def _Scatter(states, idx, blocks):
        leaves, treedef = jax.tree_util.tree_flatten(states)
        assert len(leaves) == len(axes), (len(leaves), len(axes))
        out, j = [], 0
        for leaf, ax in zip(leaves, axes):
          if ax is None:
            out.append(leaf)
          else:
            out.append(leaf.at[(slice(None),) * ax + (idx,)].set(blocks[j]))
            j += 1
        return jax.tree_util.tree_unflatten(treedef, out)

      donate = (0,) if jax.default_backend() != "cpu" else ()
      self._page_io_fns = (jax.jit(_Gather),
                           jax.jit(_Scatter, donate_argnums=donate))
    return self._page_io_fns

  # -- preemption spill/restore (scheduler_mode='priority') ------------------

  def _SlotLeafAxes(self):
    """Slot axis per decode-state leaf, None for slot-independent leaves.

    The same structural trick as _PagedLeafAxes, diffed along num_slots
    instead of the pool geometry: abstract-eval InitPagedDecodeState at
    max_batch and max_batch + 1 — the leaf axis that grew is the slot
    axis. Exactly the O(1)-mixer state leaves move (paged KV leaves are
    slot-independent; block tables route them), so this is the complete
    per-slot recurrent state a preemption must carry to the host."""
    def _Shapes(num_slots):
      return jax.eval_shape(
          lambda th: self._task.InitPagedDecodeState(
              th, self.num_pages + 1, self.page_size, num_slots,
              self._kv_override), self._theta)

    a = jax.tree_util.tree_leaves(_Shapes(self.max_batch))
    b = jax.tree_util.tree_leaves(_Shapes(self.max_batch + 1))
    axes = []
    for la, lb in zip(a, b):
      diff = [i for i, (x, y) in enumerate(zip(la.shape, lb.shape))
              if x != y]
      assert len(diff) <= 1, (la.shape, lb.shape)
      axes.append(diff[0] if diff else None)
    return axes

  def _SlotIoFns(self):
    """Jitted (gather, scatter) of ONE slot's row across every slot-axis
    leaf — the state half of preemption spill/restore."""
    if self._slot_io_fns is None:
      axes = self._SlotLeafAxes()

      def _Gather(states, slot):
        leaves = jax.tree_util.tree_leaves(states)
        assert len(leaves) == len(axes), (len(leaves), len(axes))
        return [jnp.take(leaf, slot, axis=ax)
                for leaf, ax in zip(leaves, axes) if ax is not None]

      def _Scatter(states, slot, rows):
        leaves, treedef = jax.tree_util.tree_flatten(states)
        assert len(leaves) == len(axes), (len(leaves), len(axes))
        out, j = [], 0
        for leaf, ax in zip(leaves, axes):
          if ax is None:
            out.append(leaf)
          else:
            out.append(leaf.at[(slice(None),) * ax + (slot,)].set(rows[j]))
            j += 1
        return jax.tree_util.tree_unflatten(treedef, out)

      donate = (0,) if jax.default_backend() != "cpu" else ()
      self._slot_io_fns = (jax.jit(_Gather),
                           jax.jit(_Scatter, donate_argnums=donate))
    return self._slot_io_fns

  def _SpillPages(self, pages):
    """Scheduler spill callback: device→host copies of whole pages
    across every paged leaf. Copies to host memory are FORCED before
    returning — the scheduler frees the device pages right after, so a
    lazy device view would read reallocated garbage."""
    gather, _ = self._PageIoFns()
    blocks = gather(self._states, jnp.asarray(pages, jnp.int32))
    return [np.asarray(b) for b in jax.block_until_ready(blocks)]

  def _RestorePages(self, pages, blocks):
    """Scheduler restore callback: scatters spilled host blocks into the
    freshly allocated device pages (same logical slots, new physical)."""
    _, scatter = self._PageIoFns()
    self._states = scatter(self._states, jnp.asarray(pages, jnp.int32),
                           [jnp.asarray(b) for b in blocks])

  def _SpillStateRow(self, slot: int):
    """Scheduler state-spill callback: one slot's O(1)-mixer state rows
    (every slot-axis leaf), forced to host."""
    gather, _ = self._SlotIoFns()
    rows = gather(self._states, jnp.int32(slot))
    return [np.asarray(r) for r in jax.block_until_ready(rows)]

  def _RestoreStateRow(self, slot: int, rows):
    """Scheduler state-restore callback: lands a spilled state row in
    the (possibly different) slot the sequence resumes in."""
    _, scatter = self._SlotIoFns()
    self._states = scatter(self._states, jnp.int32(slot),
                           [jnp.asarray(r) for r in rows])

  def ExportPrefixBlocks(self, prompt):
    """Donor half of the fleet page handoff: gathers this engine's
    cached full-page KV prefix of `prompt` out of its pool. Returns
    (num_pages, blocks) — blocks is the per-paged-leaf [n, ...] device
    array list, (0, []) when nothing is cached. The source pages are
    pinned (Retain) only for the duration of the gather; the blocks are
    copies, so the donor may evict or swap freely afterwards."""
    if self.prefix_cache is None:
      return 0, []
    with self._lock:
      pages, _ = self.prefix_cache.Probe(prompt)
      if not pages:
        return 0, []
      for pg in pages:
        self.alloc.Retain(pg)
      try:
        gather, _ = self._PageIoFns()
        blocks = gather(self._states, jnp.asarray(pages, jnp.int32))
        # materialize before unpinning: the gather must read the pages
        # while our Retain still guarantees nobody rewrites them
        blocks = list(jax.block_until_ready(blocks))
      finally:
        for pg in pages:
          self.alloc.Release(pg)
    return len(pages), blocks

  def AdoptPrefix(self, prompt, donor, channel=None) -> int:
    """Receiver half of the fleet page handoff (prefill/decode
    disaggregation, serving/fleet.py): copies `donor`'s cached full-page
    KV prefix for `prompt` into this engine's pool and prefix cache, so
    the next Submit of `prompt` admits as a warm prefix hit and prefill
    covers only the uncached tail. channel: optional transport applied
    to the gathered page blocks between the pools (e.g. the
    parallel/sendrecv.py ppermute lowering for multi-host fleets); None
    copies directly on the shared device. Returns tokens adopted — 0
    when either side has no cache, the donor holds nothing, or this pool
    cannot free enough pages (the caller then just prefills cold)."""
    if self.prefix_cache is None:
      return 0
    n, blocks = donor.ExportPrefixBlocks(prompt)
    if n == 0:
      return 0
    if channel is not None:
      blocks = channel.Transfer(blocks)
    with self._lock:
      already = self.prefix_cache.PeekHitTokens(prompt)
      if already >= n * self.page_size:
        return 0   # warm already — don't churn pages for a worse copy
      if self.alloc.num_free < n:
        self.prefix_cache.EvictForPressure(n - self.alloc.num_free)
        if self.alloc.num_free < n:
          return 0
      self._adopt_counter += 1
      owner = ("_adopt", self._adopt_counter)
      pages = self.alloc.Allocate(owner, n)
      _, scatter = self._PageIoFns()
      self._states = scatter(self._states, jnp.asarray(pages, jnp.int32),
                             blocks)
      # Insert retains what it keeps; Free drops our allocation ref, so
      # unadopted pages (a racing insert won) go straight back to the pool
      self.prefix_cache.Insert(prompt, pages)
      self.alloc.Free(owner)
    return n * self.page_size

  def UpdateTheta(self, theta, persist_prefix: Optional[bool] = None):
    """Hot-swaps the served checkpoint. Every cached prefix page holds
    K/V computed under the OLD theta — serving one to a new request
    would silently mix checkpoints — so the prefix cache is either
    dropped wholesale (Invalidate, the default) or, when
    `persist_prefix` (falling back to the engine's prefix_swap_persist
    knob) is True, kept as a tree of STALE nodes that the next prefill
    of each prefix refreshes in place (PrefixCache.MarkStale). In-flight
    sequences continue under the new theta, as with any mid-serving
    swap; a ModelDraft's independent draft theta is not touched (stale
    drafts cost acceptance rate, never correctness — every proposal is
    verified against the live theta)."""
    with self._lock:
      if self.serve_int8_weights:
        theta, _ = quant_weights.Int8ServingTheta(theta)
      self._theta = theta
      if self.prefix_cache is not None:
        persist = (self.prefix_swap_persist if persist_prefix is None
                   else persist_prefix)
        if persist:
          self.prefix_cache.MarkStale()
        else:
          self.prefix_cache.Invalidate()

  # -- async API -------------------------------------------------------------

  def Start(self):
    with self._lock:
      if self._running:
        return self
      self._running = True
      self._thread = threading.Thread(target=self._Loop, daemon=True,
                                      name="serving-loop")
      self._thread.start()
    return self

  def Stop(self, drain: bool = True, timeout: float = 60.0):
    """drain=True finishes in-flight + queued work first."""
    with self._lock:
      if not self._running:
        return
      if not drain:
        for h in list(self._handles.values()):
          if not h.done:
            self.Cancel(h.id)   # RLock: reentrant under self._lock
      self._work.notify_all()
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
      with self._lock:
        if not self.sched.HasWork():
          self._running = False
          self._work.notify_all()
          break
      time.sleep(0.005)
    else:
      with self._lock:
        self._running = False
        self._work.notify_all()
    if self._thread is not None:
      self._thread.join(timeout=timeout)
      self._thread = None
    if self.status_server is not None:
      self.status_server.Stop()
      self.status_server = None
    if self.watchdog is not None:
      self.watchdog.Close()   # drop any still-armed flight recorder

  def Submit(self, prompt, max_new_tokens: Optional[int] = None,
             eos_id=_END, seed: Optional[int] = None,
             spec_k: Optional[int] = None,
             spec_w: Optional[int] = None,
             priority: int = 0, tenant=None) -> StreamHandle:
    """Queues a request; returns its streaming handle immediately.

    seed: per-request sampling seed (defaults to the request id) — only
    observable at temperature > 0; same seed = same continuation.
    spec_k: per-request speculative-decoding knob — None defers to the
    engine (full draft length when a draft source is configured, exact
    legacy behavior otherwise), 0 opts out, n > 0 caps the draft length
    at min(n, engine k).
    spec_w: per-request tree-speculation WIDTH knob — None defers to the
    engine's draft width, 1 forces a linear chain (exact chain-spec
    behavior), n > 1 caps the branch count at min(n, engine w).
    priority: SLO class, higher = more urgent — consulted only under
    scheduler_mode='priority' (admission order + preemption rights);
    FIFO engines ignore it. tenant: quota/fairness label; a tenant over
    its token-rate quota gets QuotaExceeded here, before any handle or
    scheduler state is created."""
    max_new = max_new_tokens or self.default_max_new
    eos = self.eos_id if eos_id is _END else eos_id
    with self._lock:
      self._seq_counter += 1
      req_id = self._seq_counter
      req = scheduler_lib.Request(req_id, prompt, max_new, eos, seed=seed,
                                  spec_k=spec_k, spec_w=spec_w,
                                  priority=priority, tenant=tenant)
      total = len(req.prompt) + req.max_new
      if self.sched.needs_kv_pages and (
          self.alloc.PagesFor(total) > self.alloc.num_pages):
        raise ValueError(
            f"request needs {self.alloc.PagesFor(total)} pages; the pool "
            f"only has {self.alloc.num_pages} — it could never be admitted")
      self.sched.Submit(req)
      handle = StreamHandle(req_id, self, time.perf_counter())
      self._handles[req_id] = handle
      if self.trace is not None:
        self.trace.Submit(req_id, len(req.prompt), req.max_new)
      if self.watchdog is not None:
        st = self.sched.Stats()
        self.watchdog.ObserveQueue(st["queue_depth"], st["finished"])
      self._work.notify_all()
    return handle

  def Cancel(self, req_id) -> bool:
    with self._lock:
      ok = self.sched.Cancel(req_id)
      if ok:
        h = self._handles.get(req_id)
        if h is not None and not h.done:
          h._Finish("cancelled")
        if self.trace is not None:
          self.trace.Retire(req_id, "cancelled",
                            self._pages_of.pop(req_id, 0))
      return ok

  def _Loop(self):
    while True:
      with self._lock:
        if not self._running:
          return
        if not self.sched.HasWork():
          self._work.wait(timeout=0.05)
          # no work is not a stall: refresh liveness so an idle replica
          # keeps answering /healthz 200 past the no_heartbeat window
          if self.watchdog is not None:
            self.watchdog.Idle()
          continue
      self.StepOnce()

  # -- core step (shared by sync and async modes) ----------------------------

  def StepOnce(self) -> int:
    """One admit → device step → commit iteration; returns #events.

    Ragged mode (default): every iteration — any mix of prefill chunks,
    plain decode rows, and spec-verify rows — launches the ONE compiled
    packed-token program; with a draft source, rows that speculate get a
    draft pass first while prefilling neighbors ride the same step.
    Legacy mode: pure-decode iterations where at least one row
    speculates become draft → verify → commit cycles; mixed steps (and
    all-opted-out batches) take the two-program path."""
    if self.step_mode == "ragged":
      return self._StepOnceRagged()
    return self._StepOnceLegacy()

  def _AdmitPhase(self):
    """Evict + admit + per-admission bookkeeping (caller holds the lock)."""
    self.sched.EvictCancelled()
    admitted = self.sched.Admit()
    for seq in admitted:
      h = self._handles.get(seq.id)
      # a restored PREEMPTED sequence comes back through Admit too:
      # admit_time (and the prefix-hit count) belong to its FIRST
      # admission only
      first = h is None or h.admit_time is None
      if h is not None and h.admit_time is None:
        h.admit_time = time.perf_counter()
      pages = 0
      if self.sched.needs_kv_pages:
        try:
          pages = len(self.alloc.PagesOf(seq.id))
        except KeyError:
          pages = 0
      self._pages_of[seq.id] = pages
      if seq.reused_tokens > 0 and first:
        self._counters["prefix_hit_tokens"].Inc(seq.reused_tokens)
        if self.trace is not None:
          self.trace.PrefixHit(seq.id, seq.reused_tokens)
      if self.trace is not None:
        self.trace.Admit(seq.id, seq.slot, pages)
    if self.prefix_cache is not None and admitted:
      # split shared pages the new rows will write into BEFORE any step
      self._RunCow(admitted)

  def _StepOnceRagged(self) -> int:
    """One iteration through the unified ragged step program."""
    with self._lock:
      self._AdmitPhase()
      spec_k = self.spec.k if self.spec is not None else 0
      spec_w = self.spec.w if self.spec is not None else 1
      batch = self.sched.BuildRaggedStep(self._ragged_t, self._ragged_wmax,
                                         spec_k=spec_k, spec_w=spec_w)
      if batch is None:
        return 0
      tables = np.array(self.sched.block_tables)  # freeze under the lock
      window = self._profile_window
      if window is not None:
        window.Start()
    desc = batch.rows_desc
    q_logits = None
    if self.spec is not None:
      if batch.any_spec:
        # draft outside the lock (device work), exactly like the legacy
        # spec cycle; the RaggedBatch speaks the StepBatch protocol with
        # in_len > 0 only on drafting rows, so prefill rows ride the
        # step without activating the draft pass
        d_toks, q_logits = self.spec.Draft(self._theta, self._states,
                                           batch, tables)
        # one dtype for both the drafted and the no-draft (zeros) case:
        # the verify program must keep a single compiled signature
        q_logits = q_logits.astype(jnp.float32)
        # tree rows pack branch-major: branch bi's depth-d node sits at
        # packed column 1 + bi*rk + d but draft index bi*spec_k + d —
        # clamped rows (rk < spec_k) keep only each branch's prefix
        for i in range(self.max_batch):
          rk = int(batch.row_k[i])
          if rk > 0:
            for bi in range(int(batch.row_w[i])):
              batch.tok_ids[desc.row_cols[i, 1 + bi * rk:1 + (bi + 1) * rk]
                            ] = d_toks[i, bi * spec_k:bi * spec_k + rk]
      else:
        q_logits = self._ZeroQLogits()
    rows_dev = ragged_lib.RaggedRows(*(jnp.asarray(m) for m in desc))
    args = [self._theta, self._states, jnp.asarray(batch.tok_ids),
            rows_dev, jnp.asarray(tables), jnp.asarray(batch.row_seeds),
            jnp.asarray(batch.row_pos)]
    out = alen = None
    if self.spec is not None:
      args += [jnp.asarray(batch.row_k)]
      if self.spec.w > 1:
        args += [jnp.asarray(batch.row_w)]
      args += [q_logits]
      sampled, out, alen, new_states = self._compile_log.Call(
          "ragged", self._ragged_fn, *args)
      out, alen = np.asarray(out), np.asarray(alen)
    else:
      sampled, new_states = self._compile_log.Call(
          "ragged", self._ragged_fn, *args)
    self._states = new_states
    sampled = np.asarray(sampled)
    with self._lock:
      if self.trace is not None and batch.mixed:
        # emit prefill-chunk spans BEFORE commit advances the cursors
        for i, seq in enumerate(batch.rows):
          n = int(desc.row_len[i])
          if (seq is not None
              and seq.state is scheduler_lib.SeqState.PREFILL and n > 0):
            self.trace.PrefillChunk(seq.id, n)
      events = self.sched.CommitRaggedStep(batch, sampled, out, alen)
      self._counters["steps"].Inc()
      self._counters["mixed_steps" if batch.mixed else "decode_steps"].Inc()
      self._counters["prompt_tokens"].Inc(batch.prompt_tokens)
      if self.paged_path == "dense":
        self._counters["dense_fallback_steps"].Inc()
      if self._kv_quantized:
        self._counters["quantized_steps"].Inc()
      if batch.any_spec:
        self._counters["spec_cycles"].Inc()
        if batch.width_clamps:
          self._counters["spec_width_clamps"].Inc(batch.width_clamps)
        for i, seq in enumerate(batch.rows):
          rk = int(batch.row_k[i])
          if (seq is None or rk == 0
              or seq.state is scheduler_lib.SeqState.CANCELLED):
            continue
          rw = int(batch.row_w[i])
          m = min(int(alen[i]), rk)
          self._counters["draft_tokens"].Inc(rw * rk)
          self._counters["accepted_tokens"].Inc(m)
          self._counters["spec_branches"].Inc(rw)
          self.spec.accepted_len_hist[m] += 1
          if self.trace is not None:
            self.trace.SpecVerify(seq.id, rw * rk, m)
            if rw * rk - m > 0:
              self.trace.Rollback(seq.id, rw * rk - m)
      self._PushEvents(events)
      self._TickProfile()
      self._BeatWatchdog()
    return len(events)

  def _StepOnceLegacy(self) -> int:
    """One iteration through the legacy two-to-three-program engine."""
    with self._lock:
      self._AdmitPhase()
      vbatch = None
      if self.spec is not None:
        vbatch = self.sched.BuildVerifyStep(self.spec.k)
      batch = None if vbatch is not None else self.sched.BuildStep()
      if vbatch is None and batch is None:
        return 0
      tables = np.array(self.sched.block_tables)  # freeze under the lock
      window = self._profile_window
      if window is not None:
        window.Start()
    if vbatch is not None:
      return self._SpecCycle(vbatch, tables)
    sampled, new_states = self._compile_log.Call(
        "mixed" if batch.mixed else "decode", self._step_fn,
        self._theta, self._states, jnp.asarray(batch.ids),
        jnp.asarray(batch.q_pos), jnp.asarray(batch.in_len),
        jnp.asarray(tables), jnp.asarray(batch.row_seeds),
        jnp.asarray(batch.row_pos))
    self._states = new_states
    sampled = np.asarray(sampled)
    with self._lock:
      if self.trace is not None and batch.mixed:
        # emit prefill-chunk spans BEFORE CommitStep advances the cursors:
        # row i consumed in_len[i] prompt tokens starting at q_pos[i]
        for i, seq in enumerate(batch.rows):
          if (seq is not None
              and seq.state is scheduler_lib.SeqState.PREFILL
              and int(batch.in_len[i]) > 0):
            self.trace.PrefillChunk(seq.id, int(batch.in_len[i]))
      events = self.sched.CommitStep(batch, sampled)
      self._counters["steps"].Inc()
      self._counters["mixed_steps" if batch.mixed else "decode_steps"].Inc()
      self._counters["prompt_tokens"].Inc(batch.prompt_tokens)
      if self.paged_path == "dense":
        self._counters["dense_fallback_steps"].Inc()
      if self._kv_quantized:
        self._counters["quantized_steps"].Inc()
      self._PushEvents(events)
      self._TickProfile()
      self._BeatWatchdog()
    return len(events)

  def _SpecCycle(self, vbatch, tables) -> int:
    """Draft k tokens per row → ragged [B, k+1] verify → commit prefix."""
    spec = self.spec
    d_toks, q_logits = spec.Draft(self._theta, self._states, vbatch, tables)
    ids = np.array(vbatch.ids)
    ids[:, 1:] = d_toks
    vbatch.ids = ids
    out, alen, new_states = spec.Verify(
        self._theta, self._states, ids, vbatch, tables, q_logits)
    self._states = new_states
    out, alen = np.asarray(out), np.asarray(alen)
    with self._lock:
      events = self.sched.CommitVerifyStep(vbatch, out, alen)
      self._counters["steps"].Inc()
      self._counters["decode_steps"].Inc()
      self._counters["spec_cycles"].Inc()
      if self.paged_path == "dense":
        self._counters["dense_fallback_steps"].Inc()
      if self._kv_quantized:
        self._counters["quantized_steps"].Inc()
      for i, seq in enumerate(vbatch.rows):
        rk = int(vbatch.row_k[i])
        if (seq is None or rk == 0
            or seq.state is scheduler_lib.SeqState.CANCELLED):
          continue
        m = min(int(alen[i]), rk)
        self._counters["draft_tokens"].Inc(rk)
        self._counters["accepted_tokens"].Inc(m)
        self._counters["spec_branches"].Inc(1)   # legacy verify is chain
        spec.accepted_len_hist[m] += 1
        if self.trace is not None:
          self.trace.SpecVerify(seq.id, rk, m)
          if rk - m > 0:
            self.trace.Rollback(seq.id, rk - m)
      self._PushEvents(events)
      self._TickProfile()
      self._BeatWatchdog()
    return len(events)

  def _PushEvents(self, events):
    """Streams committed tokens to their handles (caller holds the lock)."""
    for req_id, tok, finished in events:
      self._counters["tokens_emitted"].Inc()
      if self.trace is not None:
        self.trace.Token(req_id)
      h = self._handles.get(req_id)
      if h is None:
        if finished and self.trace is not None:
          self.trace.Retire(req_id, self.sched._by_id[req_id].finish_reason,
                            self._pages_of.pop(req_id, 0))
        continue
      h._Push(tok)
      if finished:
        h._Finish(self.sched._by_id[req_id].finish_reason)
        if self.trace is not None:
          self.trace.Retire(req_id, h.finish_reason,
                            self._pages_of.pop(req_id, 0))
        self._ObserveLatencies(h)

  def _ObserveLatencies(self, h: StreamHandle):
    """Fills the latency histograms from the handle's lifecycle times;
    independent of whether tracing is on (caller holds the lock)."""
    if h.admit_time is not None:
      self._h_queue_wait.Observe(h.admit_time - h.submit_time)
      # per-SLO-class queue-wait histograms (priority mode): lazily
      # created per class actually seen, so fifo engines publish none
      if self.scheduler_mode == "priority":
        seq = self.sched._by_id.get(h.id)
        cls = seq.req.priority if seq is not None else 0
        hist = self._h_queue_wait_cls.get(cls)
        if hist is None:
          hist = self.metrics.Histogram(f"serving/queue_wait_s_c{cls}")
          self._h_queue_wait_cls[cls] = hist
        hist.Observe(h.admit_time - h.submit_time)
    if h.first_token_time is not None:
      self._h_ttft.Observe(h.first_token_time - h.submit_time)
      ntok = len(h._tokens)
      if ntok > 1 and h.finish_time is not None:
        self._h_tpot.Observe(
            (h.finish_time - h.first_token_time) / (ntok - 1))

  def _TickProfile(self):
    """Advances an armed N-step ProfileWindow (caller holds the lock)."""
    if self._profile_window is not None:
      if self._profile_window.StepDone():
        self._profile_window = None

  def _BeatWatchdog(self):
    """One step's liveness heartbeat + queue observation (caller holds
    the lock). The watchdog's own lock nests strictly inside the engine
    lock here; Check() runs lock-free of the engine on scrape threads."""
    if self.watchdog is not None:
      st = self.sched.Stats()
      self.watchdog.ObserveQueue(st["queue_depth"], st["finished"])
      self.watchdog.Beat()

  def ProfileSteps(self, logdir: str, steps: int = 5):
    """Arms a jax.profiler window covering the next `steps` engine steps;
    the trace lands under `<logdir>/plugins/profile/` (no-op on backends
    without profiler support). Returns the armed ProfileWindow."""
    window = observe.ProfileWindow(logdir, steps=steps)
    with self._lock:
      self._profile_window = window
    return window

  # -- sync GShardDecode-parity mode ----------------------------------------

  def RunBatch(self, prompts: np.ndarray, prompt_lens: np.ndarray,
               max_new_tokens: Optional[int] = None) -> np.ndarray:
    """Decodes a fixed prompt set inline; returns [B, max_new] int32.

    The continuous-batching twin of `GShardDecode.DecodeOnce`: same greedy
    sampling, token-identical outputs (asserted in tests), but sequences
    retire individually so the pool drains as rows finish. eos is ignored
    here (GShardDecode always decodes exactly max_decode_steps tokens)."""
    assert self._thread is None, "RunBatch drives the loop inline; Stop() first"
    prompts = np.asarray(prompts)
    max_new = max_new_tokens or self.default_max_new
    handles = []
    for i in range(prompts.shape[0]):
      ln = int(prompt_lens[i])
      handles.append(self.Submit(prompts[i, :ln], max_new, eos_id=None))
    while True:
      with self._lock:
        if not self.sched.HasWork():
          break
      self.StepOnce()
    out = np.zeros((prompts.shape[0], max_new), np.int32)
    for i, h in enumerate(handles):
      toks = h.Result(timeout=0)
      out[i, :len(toks)] = toks
    return out

  # -- introspection ---------------------------------------------------------

  def Stats(self) -> dict:
    """Atomic engine snapshot (the consistent read surface; the registry's
    Snapshot() is the lock-free best-effort view). Key set is declared in
    observe/schema.py and validated by ValidateEngineStats in tests."""
    with self._lock:
      stats = {k: c.value for k, c in self._counters.items()}
      stats["paged_path"] = self.paged_path
      stats["kv_cache_dtype"] = self.kv_cache_dtype
      stats["kv_bytes_per_token"] = self.kv_bytes_per_token
      stats["serve_int8_weights"] = self.serve_int8_weights
      stats["scheduler"] = self.sched.Stats()
      stats["kv_pages"] = self.alloc.Stats()
      stats["mixers"] = dict(self.mixers)
      stats["prefix_cache"] = (
          self.prefix_cache.Stats() if self.prefix_cache is not None
          else observe_schema.DisabledPrefixCacheStats())
      if self.state_pool is not None:
        stats["state_slots"] = self.state_pool.Stats()
      # acceptance telemetry: hist[m] = verify rows whose accepted draft
      # prefix had length m ([] for engines without a draft source).
      # accepted_depth_hist is the tree-speculation reading of the SAME
      # data — m is the accepted root-to-leaf DEPTH along the winning
      # branch (chains: depth == prefix length, so the views coincide).
      stats["accepted_len_hist"] = (
          self.spec.accepted_len_hist.tolist() if self.spec else [])
      stats["accepted_depth_hist"] = (
          self.spec.accepted_len_hist.tolist() if self.spec else [])
      if self.spec is not None:
        stats["spec"] = self.spec.Describe()
      if self.trace is not None:
        stats["trace"] = self.trace.Stats()
      if self.watchdog is not None:
        stats["watchdog"] = self.watchdog.Stats()
      records = self._compile_log.Records()
      # compiled-step-program census: how many distinct per-step programs
      # this engine has actually compiled (ragged mode: exactly 1 across
      # any admit/decode/spec/retire mix — the tentpole's acceptance bar;
      # legacy mode: up to 3). Draft programs are NOT step programs.
      records[observe_schema.COMPILE_CENSUS_KEY] = sum(
          1 for n in records if n in observe_schema.STEP_PROGRAM_NAMES)
      stats["compile"] = records
    return stats
