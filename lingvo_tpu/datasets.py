"""Dataset discovery by reflection (ref `lingvo/datasets.py`).

Every public zero-arg method of a ModelParams class that isn't part of the
base interface is a dataset (Train/Dev/Test/...); `GetDatasets` lists them,
`trainer.py --list` and the registry use it so new datasets need no
registration step.
"""

from __future__ import annotations

import inspect
from typing import Any, List

NON_DATASET_MEMBERS = [
    "GetAllDatasetParams", "GetDatasetParams", "GetDatasetNames", "Model",
    "Search", "Task", "ProgramSchedule", "UpdateParamsFromSpec",
    "CreateDynamicDatasetMethods", "Params",
]


class DatasetFunctionError(TypeError):
  pass


def GetDatasets(cls: Any, warn_on_error: bool = True) -> List[str]:
  """Returns dataset method names (e.g. ['Test', 'Train']), sorted.

  A dataset method is public, not in NON_DATASET_MEMBERS, and callable with
  no positional arguments (ref `datasets.py:34`). If `GetAllDatasetParams`
  is implemented, its keys win and reflection is skipped.
  """
  instance = None
  if inspect.isclass(cls):
    try:
      instance = cls()
    except TypeError:
      pass
  else:
    instance = cls

  # Cheap path first: GetDatasetNames reflects names WITHOUT building any
  # Params trees (GetAllDatasetParams instantiates every dataset's full
  # config — far too heavy for a listing).
  if instance is not None and hasattr(instance, "GetDatasetNames"):
    try:
      return sorted(instance.GetDatasetNames())
    except Exception:  # noqa: BLE001 - fall through to reflection
      pass

  datasets = []
  target = cls if inspect.isclass(cls) else type(cls)
  for name, fn in inspect.getmembers(target, inspect.isroutine):
    if name.startswith("_") or name in NON_DATASET_MEMBERS:
      continue
    try:
      sig_params = list(inspect.signature(fn).parameters.values())
    except (TypeError, ValueError):
      continue
    # drop self for plain functions reached through the class
    if sig_params and sig_params[0].name in ("self", "cls"):
      sig_params = sig_params[1:]
    required = [a for a in sig_params
                if a.default is inspect.Parameter.empty
                and a.kind in (inspect.Parameter.POSITIONAL_ONLY,
                               inspect.Parameter.POSITIONAL_OR_KEYWORD)]
    if required:
      msg = (f"{target.__name__}.{name} has required args and cannot be "
             f"a dataset")
      if warn_on_error:
        import logging
        logging.warning(msg)
        continue
      raise DatasetFunctionError(msg)
    datasets.append(name)
  return sorted(datasets)
