"""Global model registry: name -> ModelParams class.

Re-designs `lingvo/model_registry.py:70-389`: experiment classes register
themselves under `<task_dir>.<module>.<ClassName>` and the trainer looks them
up by name, applying a dataset method to produce the final Params tree.
"""

from __future__ import annotations

import importlib
from typing import Type

from lingvo_tpu.core import base_model_params

_MODEL_REGISTRY: dict[str, Type[base_model_params._BaseModelParams]] = {}

# Module prefixes probed by _MaybeImportFor: `lm.foo.Bar` ->
# `lingvo_tpu.models.lm.params.foo`.
_TASK_ROOT = "lingvo_tpu.models"


def _RegisterModel(cls, task_hint: str | None = None):
  module = cls.__module__
  # e.g. lingvo_tpu.models.lm.params.one_billion_wds -> lm.one_billion_wds
  parts = module.split(".")
  if "models" in parts:
    idx = parts.index("models")
    task = parts[idx + 1] if len(parts) > idx + 1 else (task_hint or "misc")
    leaf = parts[-1] if parts[-1] != "params" else task
  else:
    task, leaf = (task_hint or "misc"), parts[-1]
  key = f"{task}.{leaf}.{cls.__name__}"
  _MODEL_REGISTRY[key] = cls
  cls._registry_key = key
  return cls


def RegisterSingleTaskModel(cls):
  """Class decorator registering a SingleTaskModelParams subclass."""
  if not issubclass(cls, base_model_params.SingleTaskModelParams):
    raise TypeError(f"{cls} must subclass SingleTaskModelParams")
  return _RegisterModel(cls)


def RegisterMultiTaskModel(cls):
  if not issubclass(cls, base_model_params.MultiTaskModelParams):
    raise TypeError(f"{cls} must subclass MultiTaskModelParams")
  return _RegisterModel(cls)


def _MaybeImportFor(name: str) -> None:
  parts = name.split(".")
  if len(parts) < 3:
    return
  task, module = parts[0], parts[1]
  for candidate in (f"{_TASK_ROOT}.{task}.params.{module}",
                    f"{_TASK_ROOT}.{task}.{module}"):
    try:
      importlib.import_module(candidate)
      return
    except ModuleNotFoundError as e:
      # Only swallow "the candidate module itself doesn't exist"; a missing
      # dependency *inside* an experiment module is a real error.
      if e.name and (candidate == e.name or candidate.startswith(e.name + ".")):
        continue
      raise


def GetClass(name: str) -> Type[base_model_params._BaseModelParams]:
  if name not in _MODEL_REGISTRY:
    _MaybeImportFor(name)
  if name not in _MODEL_REGISTRY:
    known = "\n  ".join(sorted(_MODEL_REGISTRY))
    raise LookupError(f"Model {name!r} not registered. Known:\n  {known}")
  return _MODEL_REGISTRY[name]


def GetParams(name: str, dataset_name: str):
  """Returns the full model Params for `name` with `dataset_name` applied.

  Mirrors `model_registry.GetParams` (`model_registry.py:383`): instantiates
  the ModelParams class, fetches the dataset method's input params, and
  attaches them to the model params.
  """
  cls = GetClass(name)
  inst = cls()
  model_params = inst.Model()
  input_params = inst.GetDatasetParams(dataset_name)
  model_params.input = input_params
  return model_params


def GetRegisteredModels():
  return dict(_MODEL_REGISTRY)
