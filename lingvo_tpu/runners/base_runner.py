"""Checkpoint-polling runner: eval/decode jobs that follow a training run.

Re-designs the reference's polling Evaler/Decoder machinery
(`base_runner.py:224-298`: `_FindNewCheckpoint` + `_RunOnLatestCheckpoints`,
driven by `runners.py` Evaler:860 / Decoder:1105): a separate job watches the
trainer's checkpoint directory, and each time a new checkpoint appears it
restores the weights and runs its programs (eval or decode) against it,
writing summaries tagged with the checkpoint's global step. The job exits
when a checkpoint at/after the task's max_steps has been processed, or when
no new checkpoint appears within `timeout_secs`.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Sequence

import jax

from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core.nested_map import NestedMap


class CheckpointPollingRunner:
  """Runs programs against every new checkpoint in a training directory."""

  def __init__(self, task, programs: Sequence, train_dir: str,
               poll_interval_secs: float = 10.0,
               timeout_secs: float = 3600.0,
               init_seed: int = 1234):
    self._task = task
    self._programs = list(programs)
    self._train_dir = train_dir
    self._checkpointer = checkpointer_lib.Checkpointer(train_dir)
    self._poll_interval = poll_interval_secs
    self._timeout = timeout_secs
    self._init_seed = init_seed
    self._last_evaled_step = -1
    # abstract restore template, built ONCE without running initializers
    # (eval_shape traces CreateTrainState into ShapeDtypeStructs); under
    # multi-host the template carries the programs' mesh shardings so the
    # collective restore produces global arrays
    from lingvo_tpu.runners import program as program_lib
    self._template = program_lib.PlaceStateForPrograms(
        self._programs,
        jax.eval_shape(self._task.CreateTrainState,
                       jax.random.PRNGKey(self._init_seed)))

  def _FindNewCheckpoint(self) -> int | None:
    """Latest unseen checkpoint step, or None (ref _FindNewCheckpoint:224)."""
    latest = self._checkpointer.LatestStep()
    if latest is None or latest <= self._last_evaled_step:
      return None
    return latest

  def RunOnce(self, step: int) -> dict:
    """Restores checkpoint `step` and runs all programs against it."""
    state, restored_step = self._checkpointer.Restore(self._template,
                                                      step=step)
    results = {}
    for prog in self._programs:
      _, r = prog.Run(state)
      results[prog.p.name] = r
    self._last_evaled_step = restored_step
    return results

  def _TrainFinished(self) -> bool:
    return os.path.exists(os.path.join(self._train_dir, "FINISHED"))

  def Run(self, on_results: Callable[[int, dict], None] | None = None):
    """Polls until the final checkpoint is processed or timeout expires."""
    max_steps = self._task.p.train.max_steps
    last_new = time.time()
    try:
      while True:
        step = self._FindNewCheckpoint()
        if step is not None:
          results = self.RunOnce(step)
          last_new = time.time()
          print(f"[poller] evaluated checkpoint @ step {step}", flush=True)
          if on_results is not None:
            on_results(step, results)
          if step >= max_steps or self._TrainFinished():
            return  # training finished and its last checkpoint is processed
        elif self._TrainFinished():
          # trainer ended (e.g. early stop) and nothing new remains
          print("[poller] trainer FINISHED marker seen; exiting", flush=True)
          return
        elif time.time() - last_new > self._timeout:
          print(f"[poller] no new checkpoint in {self._timeout:.0f}s; "
                "exiting", flush=True)
          return
        else:
          time.sleep(self._poll_interval)
    finally:
      # orbax keeps non-daemon worker threads: without Close() the evaler
      # process never exits
      self._checkpointer.Close()
