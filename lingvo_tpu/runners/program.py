"""Programs: jit-compiled train/eval/decode units + their schedule.

Re-designs `lingvo/core/program.py` (2.9k LoC). The reference builds TF graphs
with on-device `steps_per_loop` repeats, infeed/outfeed queues and
`tpu.split_compile_and_shard`; here each program owns a jit'd step function
(optionally pjit over a mesh), a host loop that feeds device_put batches, and
weighted metric accumulators (ref `TpuEvalMetrics`). `SimpleProgramSchedule`
(ref `program.py:2329`) time-slices train/eval/decode on the same chips.
"""

from __future__ import annotations

import functools
import json
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core import metrics as metrics_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


def _StateDonation() -> tuple:
  """donate_argnums for the train-state argument: donation only buys the
  in-place update on accelerators, and the CPU backend warns 'Some donated
  buffers were not usable' for every non-aliasable leaf (same gating as
  gshard_decode's decode-state donation)."""
  return (0,) if jax.default_backend() != "cpu" else ()


def _ScalarSummaryPairs(train_out: NestedMap) -> dict:
  """In-loop `tpu_summary.scalar` values as accumulable (value, 1.0) pairs.

  Scalars recorded inside FProp (ref tpu_summary.py) ride the same
  fixed-shape metric accumulators as stats. Non-scalar tensor summaries are
  skipped: in on_device_loop mode they never leave the scan; in per-step
  mode a host can read the last step's from train_out.summaries.
  """
  out = {}
  for k, v in train_out.get("summaries", NestedMap()).FlattenItems():
    if getattr(v, "ndim", None) == 0:
      out[f"summary_{k}"] = (v, 1.0)
  return out


class BaseProgram:
  """Shared program machinery (ref BaseProgram, program.py:75)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "", "Program name (logdir subdir).")
    p.Define("task", None, "Task params.")
    p.Define("logdir", "", "Run log directory.")
    p.Define("steps_per_loop", 100, "Steps per Run() invocation.")
    p.Define("dataset_name", "Train", "Which dataset this program consumes.")
    p.Define("mesh", None, "Optional jax Mesh for sharded execution.")
    p.Define("input_sharding", None, "PartitionSpec for input batches.")
    p.Define("state_sharding_fn", None,
             "fn(state_template)->sharding pytree (pjit).")
    p.Define("write_tensorboard", True,
             "Write TensorBoard event files next to the JSONL summaries.")
    p.Define("profiler_capture_every_n_runs", 0,
             "If >0, wrap every Nth Run() in a jax.profiler trace written "
             "to <program_dir>/plugins/profile (SURVEY §5: profiling is "
             "first-class; view in XProf/TensorBoard).")
    return p

  def __init__(self, params, task=None, input_generator=None):
    self.p = params.Copy()
    self._task = task if task is not None else params.task.Instantiate()
    self._input = input_generator
    self._program_dir = os.path.join(self.p.logdir,
                                     self.p.name or type(self).__name__)
    os.makedirs(self._program_dir, exist_ok=True)
    self._step_fn = None
    self._loop_fn = None
    self._run_count = 0
    from lingvo_tpu.core import summary_utils
    self._tb = summary_utils.SummaryWriter(
        self._program_dir, enabled=self.p.write_tensorboard)
    self._rate_tracker = summary_utils.StepRateTracker()

  @property
  def task(self):
    return self._task

  @property
  def input_generator(self):
    if self._input is None:
      ip = self.p.task.input
      if ip is None:
        raise ValueError(f"Program {self.p.name}: no input params")
      from lingvo_tpu.core import input_policy
      self._input = input_policy.Instantiate(ip)
    return self._input

  @staticmethod
  def _PlaceLocalShard(x, sharding, batch_dim: int = 0):
    """One leaf of a host-local batch -> device array under `sharding`.

    Multi-process: this HOST's rows (ref InfeedContextScope per-host
    sharding) concatenate with the other processes' along `batch_dim`
    into one global array.
    """
    if jax.process_count() > 1:
      x = np.asarray(x)
      gshape = list(x.shape)
      gshape[batch_dim] *= jax.process_count()
      return jax.make_array_from_process_local_data(
          sharding, x, tuple(gshape))
    return jax.device_put(jnp.asarray(x), sharding)

  def _PutBatch(self, batch: NestedMap) -> NestedMap:
    """Host batch -> device array(s), honoring the input sharding."""
    if self.p.mesh is not None and self.p.input_sharding is not None:
      sharding = jax.sharding.NamedSharding(self.p.mesh,
                                            self.p.input_sharding)
      return batch.Transform(
          lambda x: self._PlaceLocalShard(x, sharding))
    return batch.Transform(jnp.asarray)

  def _MeshScope(self):
    """Ambient-mesh context so sharding hints inside FProps apply."""
    import contextlib
    if self.p.mesh is not None:
      from lingvo_tpu.parallel import mesh as mesh_lib
      return mesh_lib.MeshContext(self.p.mesh)
    return contextlib.nullcontext()

  def Compile(self, state: NestedMap) -> None:
    """Ahead-of-time compile with a real batch (ref Compile:355)."""
    batch = self._PutBatch(self.input_generator.GetPreprocessedInputBatch())
    fn = self._GetStepFn(state)
    if hasattr(fn, "lower"):
      with self._MeshScope():
        fn.lower(state, batch).compile()

  def _GetStepFn(self, state: NestedMap | None = None):
    raise NotImplementedError

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    raise NotImplementedError

  def SaveProgramState(self) -> dict:
    return {}

  def LoadProgramState(self, blob: dict) -> None:
    pass

  def WriteSummaries(self, step: int, values: dict[str, float]) -> None:
    if jax.process_index() != 0:
      return  # one writer per logdir (ref cluster.add_summary job gating)
    path = os.path.join(self._program_dir, "summaries.jsonl")
    with open(path, "a") as f:
      f.write(json.dumps({"step": step, **values}) + "\n")
    self._tb.Scalars(values, step)
    self._tb.Flush()

  def _ProfilerScope(self):
    """jax.profiler trace around every Nth Run (program option)."""
    import contextlib
    n = self.p.profiler_capture_every_n_runs
    self._run_count += 1
    if n > 0 and self._run_count % n == 0:
      return jax.profiler.trace(self._program_dir)
    return contextlib.nullcontext()


class TrainProgram(BaseProgram):
  """steps_per_loop training steps per Run (ref TrainProgram:441).

  The jit'd unit is a single TrainStep; the host loop feeds batches and
  donates the state buffers so theta/opt-state update in place on device.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "train"
    p.Define("base_step_seed", 1234, "Base PRNG seed for step seeds.")
    p.Define("on_device_loop", False,
             "Run all steps_per_loop inside ONE jit call (lax.scan over a "
             "stacked batch) — one host round-trip per loop instead of per "
             "step (ref tpu_training_loop.repeat, program.py:601-609). The "
             "host prefetches steps_per_loop batches and stacks them.")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:
      key = jax.random.PRNGKey(self.p.base_step_seed)
      state_shardings = None
      if (self.p.mesh is not None and self.p.state_sharding_fn is not None and
          state is not None):
        state_shardings = self.p.state_sharding_fn(state)

      def _Step(state, batch):
        if state_shardings is not None:
          state = jax.lax.with_sharding_constraint(state, state_shardings)
        new_state, out = self._task.TrainStep(state, batch, key)
        if state_shardings is not None:
          new_state = jax.lax.with_sharding_constraint(new_state,
                                                       state_shardings)
        return new_state, out

      self._step_fn = jax.jit(_Step, donate_argnums=_StateDonation())
    return self._step_fn

  def Compile(self, state: NestedMap) -> None:
    if not self.p.on_device_loop:
      return super().Compile(state)
    # shapes only: tile ONE batch rather than consuming steps_per_loop
    # real batches from a possibly-finite stream
    batch = self.input_generator.GetPreprocessedInputBatch()
    stacked = batch.Transform(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x)[None], (self.p.steps_per_loop,) + np.shape(x)))
    with self._MeshScope():
      self._GetLoopFn(state).lower(state, stacked).compile()

  def _GetLoopFn(self, state: NestedMap | None = None):
    """steps_per_loop TrainSteps as ONE jitted lax.scan over stacked batches
    (the reference's on-device training loop, program.py:601-609)."""
    if self._loop_fn is None:

      state_shardings = None
      if (self.p.mesh is not None and self.p.state_sharding_fn is not None
          and state is not None):
        state_shardings = self.p.state_sharding_fn(state)

      def _Loop(state, stacked_batches):
        key = jax.random.PRNGKey(self.p.base_step_seed)

        def _Body(carry, batch):
          state, acc, stats_acc = carry
          if state_shardings is not None:
            state = jax.lax.with_sharding_constraint(state, state_shardings)
          state, out = self._task.TrainStep(state, batch, key)
          if state_shardings is not None:
            state = jax.lax.with_sharding_constraint(state, state_shardings)
          acc = metrics_lib.AccumulateMetrics(acc, out.metrics)
          stats = NestedMap(
              {k: (v, 1.0) for k, v in out.stats.FlattenItems()})
          stats.update(_ScalarSummaryPairs(out))
          stats_acc = metrics_lib.AccumulateMetrics(stats_acc, stats)
          return (state, acc, stats_acc), ()

        # fixed-structure zero accumulators (scan carries can't grow)
        _, out_shape = jax.eval_shape(
            lambda s, b: self._task.TrainStep(s, b, key), state,
            jax.tree_util.tree_map(lambda x: x[0], stacked_batches))
        zeros = lambda m: NestedMap(
            {k: jnp.zeros((2,), jnp.float32) for k in m.keys()})
        acc0 = zeros(out_shape.metrics)
        stats0 = NestedMap({k: jnp.zeros((2,), jnp.float32)
                            for k, _ in out_shape.stats.FlattenItems()})
        stats0.update({k: jnp.zeros((2,), jnp.float32)
                       for k in _ScalarSummaryPairs(out_shape)})
        (state, acc, stats_acc), _ = jax.lax.scan(
            _Body, (state, acc0, stats0), stacked_batches)
        return state, acc, stats_acc

      self._loop_fn = jax.jit(_Loop, donate_argnums=_StateDonation())
    return self._loop_fn

  def _RefreshHostSchedules(self) -> None:
    """Host-driven schedules (DevBasedSchedule anneal-on-plateau) may change
    between runs; their values are trace-time constants, so a change must
    drop the cached jitted functions (rare — a few decays per run)."""
    key = []
    for lrn in getattr(self._task, "learners", []):
      sched = getattr(lrn, "lr_sched", None)
      if sched is None:
        continue
      if hasattr(sched, "UpdateFromHistory"):
        sched.UpdateFromHistory()
      if hasattr(sched, "HostStateKey"):
        key.append(sched.HostStateKey())
    key = tuple(key)
    if key != getattr(self, "_host_sched_key", None):
      if getattr(self, "_host_sched_key", None) is not None:
        self._loop_fn = None
        self._step_fn = None
      self._host_sched_key = key

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    p = self.p
    t0 = time.time()
    self._RefreshHostSchedules()
    if p.on_device_loop:
      # host: prefetch + stack steps_per_loop batches; device: one program
      batches = [self.input_generator.GetPreprocessedInputBatch()
                 for _ in range(p.steps_per_loop)]
      stacked = jax.tree_util.tree_map(
          lambda *xs: np.stack(xs), *batches)
      if self.p.mesh is not None and self.p.input_sharding is not None:
        # the stacked leading dim is the STEPS axis: keep it unsharded and
        # shift the per-step batch spec right by one
        spec = jax.sharding.PartitionSpec(None, *self.p.input_sharding)
        sharding = jax.sharding.NamedSharding(self.p.mesh, spec)
        stacked = stacked.Transform(
            lambda x: self._PlaceLocalShard(x, sharding, batch_dim=1))
      else:
        stacked = stacked.Transform(jnp.asarray)
      fn = self._GetLoopFn(state)
      with self._MeshScope(), self._ProfilerScope():
        state, acc, stats_acc = fn(state, stacked)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    else:
      fn = self._GetStepFn(state)
      acc = None
      stats_acc = None
      with self._MeshScope(), self._ProfilerScope():
        for _ in range(p.steps_per_loop):
          batch = self._PutBatch(
              self.input_generator.GetPreprocessedInputBatch())
          state, out = fn(state, batch)
          acc = metrics_lib.AccumulateMetrics(acc, out.metrics)
          stats_pairs = NestedMap(
              {k: (v, 1.0) for k, v in out.stats.FlattenItems()})
          stats_pairs.update(_ScalarSummaryPairs(out))
          stats_acc = metrics_lib.AccumulateMetrics(stats_acc, stats_pairs)
        # One host sync per loop (ref: one session.run per steps_per_loop);
        # inside the profiler scope so traces capture the device work.
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    wall = time.time() - t0
    result = metrics_lib.FinalizeMetrics(acc) if acc else {}
    if stats_acc:
      result.update(metrics_lib.FinalizeMetrics(stats_acc))
    result["steps_per_second"] = p.steps_per_loop / wall
    result["examples_per_second"] = (
        p.steps_per_loop * self.input_generator.GlobalBatchSize() / wall)
    step = int(jax.device_get(state.step))
    # smoothed cross-Run rate incl. eval gaps (ref StepRateTracker:393)
    result["global_steps_per_second"] = self._rate_tracker.Update(
        step, self.input_generator.GlobalBatchSize())
    self.WriteSummaries(step, result)
    return state, result


class EvalProgram(BaseProgram):
  """Whole-dataset eval with fixed-shape metric accumulation
  (ref EvalProgram:995)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "eval"
    p.dataset_name = "Test"
    p.Define("use_ema", True, "Eval with EMA weights when available.")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:

      def _Step(theta, batch, step):
        metrics, _ = self._task.EvalStep(theta, batch, step=step)
        return metrics

      self._step_fn = jax.jit(_Step)
    return self._step_fn

  def _EvalTheta(self, state: NestedMap) -> NestedMap:
    if self.p.use_ema and "ema_theta" in state:
      return state.ema_theta
    return state.theta

  def _MaxEvalBatches(self) -> int:
    """Eval budget: task's eval.samples_per_summary wins over steps_per_loop
    (ref base_model.py eval params; 0 = unlimited for finite datasets)."""
    sps = getattr(self._task.p.eval, "samples_per_summary", 0)
    if sps:
      # each coordinated step consumes a GLOBAL batch (all hosts' shards)
      bs = max(1, self.input_generator.InfeedBatchSize()
               * jax.process_count())
      return max(1, -(-sps // bs))
    return self.p.steps_per_loop

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    fn = self._GetStepFn(state)
    theta = self._EvalTheta(state)
    acc = None
    gen = self.input_generator
    max_batches = self._MaxEvalBatches()
    batches = _CoordinateFiniteStream(
        gen.EpochBatches() if hasattr(gen, "EpochBatches")
        else _TakeN(gen, max_batches))
    n = 0
    with self._MeshScope(), self._ProfilerScope():
      for batch in batches:
        out = fn(theta, self._PutBatch(batch), state.step)
        acc = metrics_lib.AccumulateMetrics(acc, out)
        n += 1
        if n >= max_batches:
          break
    result = metrics_lib.FinalizeMetrics(acc) if acc else {}
    _MaybeResetFiniteStream(gen)
    step = int(jax.device_get(state.step))
    self.WriteSummaries(step, result)
    return state, result


class DecodeProgram(BaseProgram):
  """Device decode + host postprocess into decoder metrics
  (ref DecodeProgram:1229)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "decode"
    p.dataset_name = "Test"
    p.Define("use_ema", True, "Decode with EMA weights when available.")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:

      def _Step(theta, batch):
        with py_utils.EvalContext():
          return self._task.Decode(theta, batch)

      self._step_fn = jax.jit(_Step)
    return self._step_fn

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    fn = self._GetStepFn(state)
    theta = (state.ema_theta
             if self.p.use_ema and "ema_theta" in state else state.theta)
    dec_metrics = self._task.CreateDecoderMetrics()
    gen = self.input_generator
    batches = _CoordinateFiniteStream(
        gen.EpochBatches() if hasattr(gen, "EpochBatches")
        else _TakeN(gen, self.p.steps_per_loop))
    n = 0
    # async host postprocess (ref DecodeProgram:1487-1529): the device
    # decodes batch k+1 while ONE worker thread postprocesses batch k.
    # One outstanding future max: bounded memory (host_out trees are big)
    # and exceptions surface within one batch, while keeping the k/k+1
    # overlap. Single worker => decoder metrics mutate without locks.
    from concurrent.futures import ThreadPoolExecutor
    pending = None
    with self._MeshScope(), self._ProfilerScope(), \
         ThreadPoolExecutor(max_workers=1) as pool:
      for batch in batches:
        out = fn(theta, self._PutBatch(batch))
        if jax.process_count() > 1:
          # batch-sharded outputs are not host-addressable: gather the
          # global tree so postprocess sees every example (every process
          # computes identical metrics; only process 0 writes). Global
          # fully-replicated leaves (scalar counters, reduced statistics a
          # task adds to its Decode output) skip the collective — every
          # process already holds the value; everything else (global
          # batch-sharded arrays, host-local or numpy leaves that differ
          # per process) goes through process_allgather as before.
          from jax.experimental import multihost_utils

          def _GatherLeaf(leaf):
            if (isinstance(leaf, jax.Array)
                and not leaf.is_fully_addressable
                and leaf.is_fully_replicated):
              return np.asarray(leaf.addressable_shards[0].data)
            return multihost_utils.process_allgather(leaf, tiled=True)

          out = jax.tree_util.tree_map(_GatherLeaf, out)
        host_out = jax.tree_util.tree_map(np.asarray, out)
        if n == 0 and isinstance(host_out, NestedMap) and (
            jax.process_index() == 0):
          probs = host_out.Get("atten_probs")
          if probs is not None:
            from lingvo_tpu.core import summary_utils
            summary_utils.AddAttentionSummary(
                self._tb, f"{self.p.name}/atten", probs,
                int(jax.device_get(state.step)))
        if pending is not None:
          pending.result()  # backpressure + surface exceptions promptly
        pending = pool.submit(self._task.PostProcessDecodeOut, host_out,
                              dec_metrics)
        n += 1
        if n >= self.p.steps_per_loop:
          break
      if pending is not None:
        pending.result()
    result = self._task.DecodeFinalize(dec_metrics)
    _MaybeResetFiniteStream(gen)
    step = int(jax.device_get(state.step))
    self.WriteSummaries(step, result)
    return state, result


class InputBenchmarkProgram(BaseProgram):
  """Measures input-pipeline throughput without touching the model (ref
  `InputBenchmark:2249`): drains steps_per_loop batches from the generator
  and reports batches/sec + examples/sec."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "input_benchmark"
    p.Define("warmup_batches", 2, "Batches drawn before timing starts.")
    return p

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    gen = self.input_generator
    for _ in range(self.p.warmup_batches):
      gen.GetPreprocessedInputBatch()
    t0 = time.time()
    n = examples = 0
    for _ in range(self.p.steps_per_loop):
      batch = gen.GetPreprocessedInputBatch()
      batched = [l for l in batch.Flatten() if np.ndim(l) >= 1]
      examples += int(batched[0].shape[0]) if batched else 0
      n += 1
    wall = max(time.time() - t0, 1e-9)
    result = {
        "batches_per_second": n / wall,
        "examples_per_second": examples / wall,
    }
    step = int(jax.device_get(state.step)) if hasattr(state, "step") else 0
    self.WriteSummaries(step, result)
    return state, result


def PlaceStateForPrograms(programs, state):
  """Places (or, for an abstract template, annotates) a train state onto
  the mesh shardings of whichever program declares them.

  Multi-host REQUIRES this before any collective orbax restore/save or
  mesh-spanning jit: host-local SingleDeviceSharding state is rejected.
  Works for any schedule shape — scans the given programs rather than
  assuming a single train program.
  """
  shardings = None
  for prog in programs:
    pp = prog.p if hasattr(prog, "p") else prog
    try:
      mesh_ = pp.mesh
      fn = pp.state_sharding_fn
    except (AttributeError, KeyError):
      continue  # program stub without mesh params (tests, custom runners)
    if mesh_ is not None and fn is not None:
      shardings = fn(state)
      break
  if shardings is None:
    return state
  leaves = jax.tree_util.tree_leaves(state)
  if leaves and isinstance(leaves[0], jax.ShapeDtypeStruct):
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state, shardings)
  return jax.device_put(state, shardings)


def _MaybeResetFiniteStream(gen):
  """Finite (max_epochs-bounded) file streams must be re-read from the start
  on the next eval round (ref EvalProgram infeed-until-OutOfRange re-setup,
  `program.py:995`); infinite streams keep their position."""
  if getattr(getattr(gen, "p", None), "max_epochs", 0):
    gen.Reset()


def _TakeN(gen, n):
  it = iter(gen)
  for _ in range(n):
    try:
      yield next(it)
    except StopIteration:
      return


def _CoordinateFiniteStream(batches):
  """Multi-host barrier on batch availability: hosts with disjoint finite
  input shards can yield UNEQUAL batch counts; since every program step is
  a cross-process collective, a host iterating one batch more than another
  deadlocks. Stops ALL hosts as soon as ANY host runs dry (the tail
  examples on longer shards are skipped — the price of collective eval;
  ref the infeed-until-OutOfRange coordination in program.py:1386)."""
  if jax.process_count() <= 1:
    yield from batches
    return
  from jax.experimental import multihost_utils
  it = iter(batches)
  while True:
    try:
      batch = next(it)
      have = True
    except StopIteration:
      batch = None
      have = False
    counts = multihost_utils.process_allgather(
        np.asarray([1 if have else 0]))
    if not bool(np.all(counts)):
      return
    yield batch


class SimpleProgramSchedule:
  """Train K loops, then run eval/decode programs
  (ref SimpleProgramSchedule:2329)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "schedule", "Name.")
    p.Define("train_program", None, "TrainProgram params (or None).")
    p.Define("eval_programs", [], "List of eval/decode program params.")
    p.Define("train_executions_per_eval", 1,
             "Train Run() calls between eval rounds.")
    return p

  def __init__(self, params, task=None, input_generators=None):
    self.p = params.Copy()
    input_generators = input_generators or {}
    self.train_program = None
    if self.p.train_program is not None:
      self.train_program = self.p.train_program.cls(
          self.p.train_program, task=task,
          input_generator=input_generators.get(
              self.p.train_program.dataset_name))
    self.eval_programs = [
        ep.cls(ep, task=task,
               input_generator=input_generators.get(ep.dataset_name))
        for ep in self.p.eval_programs
    ]

  @property
  def programs(self):
    out = []
    if self.train_program:
      out.append(self.train_program)
    return out + list(self.eval_programs)

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, Any]]:
    results: dict[str, Any] = {}
    if self.train_program is not None:
      train_result = None
      for _ in range(max(1, self.p.train_executions_per_eval)):
        state, train_result = self.train_program.Run(state)
      results["train"] = train_result
    for ep in self.eval_programs:
      state, r = ep.Run(state)
      results[ep.p.name] = r
    return state, results


class MultiTaskProgramSchedule:
  """Per-task train programs driven by a sampling TaskScheduler.

  The executor-side expansion of a MultiTaskModel (ref
  `executor.py:67-153` GetExecutorParams + the per-cycle
  `task_scheduler.Sample` at `executor.py:573`, and `SampleTask` in
  `base_model.py:1480`): each cycle samples one task name and runs that
  task's TrainProgram for its steps_per_loop. The combined train state is
  NestedMap(tasks={name: per-task state}, step=total steps) so a single
  checkpointer handles save/restore for the whole model.
  """

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "multitask_schedule", "Name.")
    p.Define("task_schedule", None, "TaskScheduler params.")
    p.Define("train_programs", None,
             "Params holding one TrainProgram params per task name.")
    p.Define("eval_programs", [], "Eval/decode program params (any task).")
    p.Define("train_executions_per_eval", 1,
             "Train cycles between eval rounds (ref "
             "SimpleProgramSchedule.train_executions_per_eval).")
    p.Define("variable_renaming_rules", [],
             "[(regex, replacement)] over dotted theta paths; tasks whose renamed "
             "paths collide share those variables (ref multitask_model.py "
             "RegExSharedVariableModel). Shared values are unified at init "
             "and propagated from the sampled task after each train cycle.")
    return p

  def __init__(self, params, tasks: dict | None = None,
               input_generators: dict | None = None, task=None):
    """tasks: {task_name: task instance} (instantiated from each train
    program's task params when omitted — the trainer CLI path);
    input_generators: {(task_name, dataset_name): generator}, or
    {dataset_name: generator} applied to every task. `task` is accepted for
    SimpleProgramSchedule constructor compatibility and ignored when `tasks`
    is given."""
    del task  # the multi-task schedule owns its task set
    self.p = params.Copy()
    input_generators = input_generators or {}
    if tasks is None:
      tasks = {}
      for name, tp in self.p.train_programs.IterParams():
        tasks[name] = tp.task.Instantiate()
        tasks[name].FinalizePaths()
    self._tasks = dict(tasks)
    self._scheduler = self.p.task_schedule.Instantiate()
    self._runs_since_eval = 0
    self._shared_rules = None
    if self.p.variable_renaming_rules:
      from lingvo_tpu.core import multitask_model
      self._shared_rules = multitask_model.SharedVariableRules(
          self.p.variable_renaming_rules)

    def _GenFor(name, dataset):
      if (name, dataset) in input_generators:
        return input_generators[(name, dataset)]
      return input_generators.get(dataset)

    self.train_programs = {}
    for name, tp in self.p.train_programs.IterParams():
      self.train_programs[name] = tp.cls(
          tp, task=tasks[name],
          input_generator=_GenFor(name, tp.dataset_name))
    self.eval_programs = []
    for ep in self.p.eval_programs:
      task_name = getattr(ep, "task_name", None) or next(iter(tasks))
      self.eval_programs.append(
          ep.cls(ep, task=tasks[task_name],
                 input_generator=_GenFor(task_name, ep.dataset_name)))

  @property
  def programs(self):
    return list(self.train_programs.values()) + list(self.eval_programs)

  @property
  def tasks(self):
    return dict(self._tasks)

  def CreateTrainState(self, key) -> NestedMap:
    import jax
    states = NestedMap()
    keys = jax.random.split(key, len(self._tasks))
    for k, name in zip(keys, sorted(self._tasks)):
      states.Set(name, self._tasks[name].CreateTrainState(k))
    if self._shared_rules is not None:
      states = self._shared_rules.UnifyStates(states)
    return NestedMap(tasks=states, step=jnp.zeros((), jnp.int32))

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, Any]]:
    import jax
    total_step = int(jax.device_get(state.step))
    name = self._scheduler.Sample(total_step)
    task_state = state.tasks.GetItem(name)
    task_state, result = self.train_programs[name].Run(task_state)
    state.tasks.Set(name, task_state)
    if self._shared_rules is not None:
      state.tasks = self._shared_rules.Propagate(state.tasks, name)
    state.step = jnp.asarray(
        sum(int(jax.device_get(state.tasks.GetItem(n).step))
            for n in sorted(self._tasks)), jnp.int32)
    results = {f"train_{name}": result, "sampled_task": name}
    self._runs_since_eval += 1
    if self._runs_since_eval >= max(1, self.p.train_executions_per_eval):
      self._runs_since_eval = 0
      for ep in self.eval_programs:
        task_name = (getattr(ep.p, "task_name", None)
                     or next(iter(self._tasks)))
        st, r = ep.Run(state.tasks.GetItem(task_name))
        state.tasks.Set(task_name, st)
        results[ep.p.name] = r
    return state, results
