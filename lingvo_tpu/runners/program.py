"""Programs: jit-compiled train/eval/decode units + their schedule.

Re-designs `lingvo/core/program.py` (2.9k LoC). The reference builds TF graphs
with on-device `steps_per_loop` repeats, infeed/outfeed queues and
`tpu.split_compile_and_shard`; here each program owns a jit'd step function
(optionally pjit over a mesh), a host loop that feeds device_put batches, and
weighted metric accumulators (ref `TpuEvalMetrics`). `SimpleProgramSchedule`
(ref `program.py:2329`) time-slices train/eval/decode on the same chips.
"""

from __future__ import annotations

import collections
import functools
import json
import os
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import observe
from lingvo_tpu.observe import goodput as goodput_lib
from lingvo_tpu.core import base_layer
from lingvo_tpu.core import hyperparams
from lingvo_tpu.core import metrics as metrics_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


def _StateDonation() -> tuple:
  """donate_argnums for the train-state argument: donation only buys the
  in-place update on accelerators, and the CPU backend warns 'Some donated
  buffers were not usable' for every non-aliasable leaf (same gating as
  gshard_decode's decode-state donation)."""
  return (0,) if jax.default_backend() != "cpu" else ()


def _ScalarSummaryPairs(train_out: NestedMap) -> dict:
  """In-loop `tpu_summary.scalar` values as accumulable (value, 1.0) pairs.

  Scalars recorded inside FProp (ref tpu_summary.py) ride the same
  fixed-shape metric accumulators as stats. Non-scalar tensor summaries are
  skipped: in on_device_loop mode they never leave the scan; in per-step
  mode a host can read the last step's from train_out.summaries.
  """
  out = {}
  for k, v in train_out.get("summaries", NestedMap()).FlattenItems():
    if getattr(v, "ndim", None) == 0:
      out[f"summary_{k}"] = (v, 1.0)
  return out


class BaseProgram:
  """Shared program machinery (ref BaseProgram, program.py:75)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "", "Program name (logdir subdir).")
    p.Define("task", None, "Task params.")
    p.Define("logdir", "", "Run log directory.")
    p.Define("steps_per_loop", 100, "Steps per Run() invocation.")
    p.Define("dataset_name", "Train", "Which dataset this program consumes.")
    p.Define("mesh", None, "Optional jax Mesh for sharded execution.")
    p.Define("input_sharding", None, "PartitionSpec for input batches.")
    p.Define("state_sharding_fn", None,
             "fn(state_template)->sharding pytree (pjit).")
    p.Define("write_tensorboard", True,
             "Write TensorBoard event files next to the JSONL summaries.")
    p.Define("profiler_capture_every_n_runs", 0,
             "If >0, wrap every Nth Run() in a jax.profiler trace written "
             "to <program_dir>/plugins/profile (SURVEY §5: profiling is "
             "first-class; view in XProf/TensorBoard).")
    p.Define("async_infeed", True,
             "Overlap host batch prep (+ H2D placement) with device compute "
             "via a background producer thread (runners/infeed.py), and — "
             "for TrainProgram — defer the post-loop metric fetch + summary "
             "writes to a background worker. False restores the exact "
             "fully-synchronous legacy control flow (kill switch).")
    p.Define("infeed_depth", 2,
             "Bounded infeed queue depth: stacked loop batches for "
             "on_device_loop, single batches otherwise.")
    p.Define("infeed_place_on_device", None,
             "Where H2D placement happens under async_infeed: True = on the "
             "producer thread (transfer overlaps compute too), False = "
             "numpy in the thread, placement on the consumer, None = auto "
             "(True single-process; multi-process, producer-side iff the "
             "one-shot off-main-thread safety probe of "
             "make_array_from_process_local_data passes — "
             "infeed.ProbeProducerPlacement — else the numpy+consumer "
             "fallback).")
    return p

  def __init__(self, params, task=None, input_generator=None):
    self.p = params.Copy()
    self._task = task if task is not None else params.task.Instantiate()
    self._input = input_generator
    self._program_dir = os.path.join(self.p.logdir,
                                     self.p.name or type(self).__name__)
    os.makedirs(self._program_dir, exist_ok=True)
    self._step_fn = None
    self._loop_fn = None
    self._run_count = 0
    self._profiling_run = False
    # async-infeed machinery (runners/infeed.py), created lazily on the
    # first async Run so Compile() can pull warm-up batches without racing
    # the producer thread for the input stream
    self._infeed = None
    self._telemetry = None
    self._pending_telemetry = None
    self._pending_consumed = True  # was the pending result already returned?
    # k-deep dispatch window (pipeline_depth >= 1): unresolved telemetry
    # futures, oldest first. The legacy lag-1 fields above stay the
    # pipeline_depth=0 kill-switch path, byte-for-byte.
    self._pending: collections.deque = collections.deque()
    self._last_result: dict | None = None
    self._last_result_consumed = True
    # completed-but-unpolled results for the executor's telemetry-driven
    # cadence decisions (NaN-stop etc.); every result that resolves through
    # the window lands here exactly once until PollCompletedResults drains it
    self._completed_unpolled: list = []
    # executor hook fired when one dispatched loop's device work +
    # telemetry completes (watchdog heartbeat); may run on the worker thread
    self._loop_done_cb: Callable[[], None] | None = None
    # host-side step tracking: after a successful loop the step is
    # deterministic (start + loops x steps_per_loop); None = unseeded (the
    # first pipelined Run seeds it from the concrete restored state, a
    # fence that already exists)
    self._host_step: int | None = None
    # pipelined goodput attribution marks (completion-interval based;
    # see _AttributePipelinedLoop): None = not yet in a pipelined window
    self._pipe_t_mark: float | None = None
    self._pipe_wait_mark = 0.0
    self._pipe_compile_mark = 0.0
    from lingvo_tpu.core import summary_utils
    self._tb = summary_utils.SummaryWriter(
        self._program_dir, enabled=self.p.write_tensorboard)
    # train-side observability publishes to the process-global registry
    # (one trainer per process; serving engines use per-instance ones)
    self.metrics = observe.Default()
    # all programs feed ONE process-global goodput tracker, so the
    # buckets partition a single wall clock (observe/goodput.py)
    self._goodput = goodput_lib.Get()
    self._rate_tracker = summary_utils.StepRateTracker(
        registry=self.metrics, name=self.p.name or "train")
    # {program_name: compile record} — wall time + XLA memory plan of each
    # AOT Compile() (observe.CompileInfo); also published as train gauges
    self.compile_records: dict = {}
    # live generator-side counters (SequenceBatcher stats, prefetch depth);
    # lazy via self._input so the snapshot never instantiates the generator
    self.metrics.SectionFn(
        f"infeed/{self.p.name or type(self).__name__}_input",
        lambda: (self._InputStatsOf(self._input)
                 if self._input is not None else {}))

  @property
  def task(self):
    return self._task

  @property
  def input_generator(self):
    if self._input is None:
      ip = self.p.task.input
      if ip is None:
        raise ValueError(f"Program {self.p.name}: no input params")
      from lingvo_tpu.core import input_policy
      self._input = input_policy.Instantiate(ip)
    return self._input

  @staticmethod
  def _PlaceLocalShard(x, sharding, batch_dim: int = 0):
    """One leaf of a host-local batch -> device array under `sharding`.

    Multi-process: this HOST's rows (ref InfeedContextScope per-host
    sharding) concatenate with the other processes' along `batch_dim`
    into one global array.
    """
    if jax.process_count() > 1:
      x = np.asarray(x)
      gshape = list(x.shape)
      gshape[batch_dim] *= jax.process_count()
      return jax.make_array_from_process_local_data(
          sharding, x, tuple(gshape))
    return jax.device_put(jnp.asarray(x), sharding)

  def _PutBatch(self, batch: NestedMap) -> NestedMap:
    """Host batch -> device array(s), honoring the input sharding."""
    if self.p.mesh is not None and self.p.input_sharding is not None:
      sharding = jax.sharding.NamedSharding(self.p.mesh,
                                            self.p.input_sharding)
      return batch.Transform(
          lambda x: self._PlaceLocalShard(x, sharding))
    return batch.Transform(jnp.asarray)

  def _MeshScope(self):
    """Ambient-mesh context so sharding hints inside FProps apply."""
    import contextlib
    if self.p.mesh is not None:
      from lingvo_tpu.parallel import mesh as mesh_lib
      return mesh_lib.MeshContext(self.p.mesh)
    return contextlib.nullcontext()

  def Compile(self, state: NestedMap) -> None:
    """Ahead-of-time compile with a real batch (ref Compile:355)."""
    batch = self._PutBatch(self.input_generator.GetPreprocessedInputBatch())
    fn = self._GetStepFn(state)
    if hasattr(fn, "lower"):
      with self._MeshScope():
        self._RecordCompile("step", fn, state, batch)

  def _RecordCompile(self, name: str, fn, *args) -> None:
    """AOT-compiles `fn(*args)` once, recording wall time + the XLA memory
    plan into `self.compile_records[name]` and the registry (ISSUE 12
    pillar 3: per-compiled-program records for train/eval programs).
    Dispatch behavior is unchanged: like the previous Compile(), the
    executable is discarded and Run keeps calling the jit wrapper."""
    t0 = time.perf_counter()
    # exclude the listener-attributed backend-compile seconds so the AOT
    # window's remainder (lowering glue) is all that lands here extra
    with self._goodput.TrackExcludingCompile("compile"):
      compiled = fn.lower(*args).compile()
    rec = {"name": name,
           "compile_wall_s": round(time.perf_counter() - t0, 6)}
    rec.update(observe.CompileInfo(compiled))
    from lingvo_tpu.core import computation_cost
    try:
      flops = float(computation_cost.CostAnalysisOf(compiled).get(
          "flops", 0.0))
    except Exception:  # noqa: BLE001 - cost analysis is backend-optional
      flops = 0.0
    if flops > 0:
      rec["flops"] = flops
    self.compile_records[name] = rec
    ns = self.p.name or type(self).__name__
    self.metrics.Gauge(
        f"{ns}/compile/{name}_wall_s").Set(rec["compile_wall_s"])
    if "temp_bytes" in rec:
      self.metrics.Gauge(
          f"{ns}/compile/{name}_temp_bytes").Set(rec["temp_bytes"])
    self._OnCompileRecord(name, rec)

  def _OnCompileRecord(self, name: str, rec: dict) -> None:
    """Subclass hook after every AOT compile record (TrainProgram uses it
    to derive flops/step and publish `train/mfu`)."""

  def _GetStepFn(self, state: NestedMap | None = None):
    raise NotImplementedError

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    raise NotImplementedError

  def SaveProgramState(self) -> dict:
    return {}

  def LoadProgramState(self, blob: dict) -> None:
    pass

  def WriteSummaries(self, step: int, values: dict[str, float]) -> None:
    self._PublishRunMetrics(values)  # every process: registry is local
    if jax.process_index() != 0:
      return  # one writer per logdir (ref cluster.add_summary job gating)
    path = os.path.join(self._program_dir, "summaries.jsonl")
    with open(path, "a") as f:
      f.write(json.dumps({"step": step, **values}) + "\n")
    self._tb.Scalars(values, step)
    self._tb.Flush()

  def _PublishRunMetrics(self, values: dict) -> None:
    """Mirrors a Run's result dict into the process registry as gauges.

    WriteSummaries is the single result sink for every program kind, so
    hooking here covers train/eval/decode/input-benchmark uniformly.
    Namespacing: input-pipeline keys (`input_*`, `infeed_*`) land under
    `infeed/*` (the schema's pipeline namespace); everything else under
    `<program name>/*`. Non-numeric values are skipped — they belong to
    the JSONL record, not the metric surface."""
    ns = self.p.name or type(self).__name__
    for k, v in values.items():
      if isinstance(v, bool) or not isinstance(v, (int, float)):
        continue
      if k.startswith("input_"):
        name = f"infeed/{k}"
      elif k.startswith("infeed_"):
        name = f"infeed/{ns}_{k[len('infeed_'):]}"
      else:
        name = f"{ns}/{k}"
      self.metrics.Gauge(name).Set(v)

  def _ProfilerScope(self):
    """jax.profiler trace around every Nth Run (program option), via
    observe.ProfileWindow — same `<program_dir>/plugins/profile/<ts>`
    layout jax.profiler.trace wrote, but degrades to a no-op instead of
    raising on backends without profiler support."""
    import contextlib
    n = self.p.profiler_capture_every_n_runs
    self._run_count += 1
    self._profiling_run = n > 0 and self._run_count % n == 0
    if self._profiling_run:
      return observe.ProfileWindow(self._program_dir)
    return contextlib.nullcontext()

  # -- async infeed / deferred telemetry lifecycle ---------------------------

  def _PlaceInProducer(self) -> bool:
    """Auto policy for where H2D placement runs (see infeed_place_on_device):
    single-process always places on the producer; multi-process asks the
    one-shot `make_array_from_process_local_data` safety probe and falls
    back to numpy-in-thread + consumer placement when it fails."""
    if self.p.infeed_place_on_device is not None:
      return bool(self.p.infeed_place_on_device)
    if jax.process_count() == 1:
      return True
    from lingvo_tpu.runners import infeed as infeed_lib
    return infeed_lib.ProbeProducerPlacement()

  @staticmethod
  def _InputStatsOf(gen) -> dict:
    """Generator-side counters (SequenceBatcher stats, prefetch depth) for
    the train summaries; {} when the generator doesn't expose them."""
    fn = getattr(gen, "InputStats", None)
    if not callable(fn):
      return {}
    try:
      return dict(fn())
    except Exception:  # noqa: BLE001 - stats must never kill a train loop
      return {}

  def SetLoopDoneCallback(self, cb: Callable[[], None] | None) -> None:
    """Executor hook: `cb` fires each time one dispatched loop's device
    work + telemetry completes (on the telemetry worker thread for deferred
    loops, inline otherwise). The executor wires the stall watchdog's
    Beat() here, so liveness tracks device COMPLETION, not host dispatch —
    a hung device behind a free-running pipelined host stops beating."""
    self._loop_done_cb = cb

  def _NotifyLoopDone(self) -> None:
    cb = self._loop_done_cb
    if cb is not None:
      try:
        cb()
      except BaseException:  # noqa: BLE001 - liveness must not kill the loop
        pass

  def SyncHostStep(self, step: int) -> None:
    """Seeds host-side step tracking at a device fence (restore, recovery).
    Between fences the pipelined paths advance the step arithmetically
    instead of fetching `state.step` from the device."""
    self._host_step = int(step)

  def _PopPending(self) -> dict:
    """Resolves the OLDEST pending loop (blocking); its result becomes the
    newest completed result and joins the unpolled cadence stream."""
    res = self._pending.popleft().result()[1]
    self._last_result = res
    self._last_result_consumed = False
    self._completed_unpolled.append(res)
    return res

  def PollCompletedResults(self) -> list:
    """Drains (without blocking) every result that completed since the last
    poll — the executor's telemetry-driven cadence stream. Each result
    appears exactly once; staleness is bounded by the dispatch window
    (<= pipeline_depth unresolved loops at any Run exit)."""
    while self._pending and self._pending[0].done():
      self._PopPending()
    out, self._completed_unpolled = self._completed_unpolled, []
    return out

  def PendingLoops(self) -> int:
    """Unresolved dispatched loops (k-deep window + the legacy lag-1 slot)."""
    return len(self._pending) + (1 if self._pending_telemetry is not None
                                 else 0)

  def Flush(self):
    """Waits for ALL deferred telemetry and flushes the TB writer; returns
    the newest completed result if no Run handed it out yet, else None.
    Called by schedules at program boundaries and by the executor at
    decision boundaries (eval, save, stop) and before the final checkpoint,
    so summaries land in order and the lagged tail result still reaches
    NaN-stop/metrics. No-op for fully-synchronous programs."""
    out = None
    if self._pending_telemetry is not None:   # legacy lag-1 window
      res = self._pending_telemetry.result()[1]
      if not self._pending_consumed:
        out = res
      self._pending_telemetry = None
      self._pending_consumed = True
    while self._pending:                      # k-deep window
      self._PopPending()
    if not self._last_result_consumed:
      out = self._last_result
      self._last_result_consumed = True
    self._tb.Flush()
    return out

  def RecoverFromFailure(self) -> None:
    """Executor retry hook: drain pending telemetry (swallowing the error
    already being handled upstream) and restart an errored infeed producer
    so the retried Run pulls fresh batches."""
    fut, self._pending_telemetry = self._pending_telemetry, None
    self._pending_consumed = True
    if fut is not None:
      try:
        fut.result()
      except BaseException:  # noqa: BLE001
        pass
    while self._pending:
      try:
        self._pending.popleft().result()
      except BaseException:  # noqa: BLE001
        pass
    # results straddling the failure are unreliable; the restore that
    # follows re-seeds the host step and the goodput interval marks
    self._last_result = None
    self._last_result_consumed = True
    self._completed_unpolled = []
    self._host_step = None
    self._pipe_t_mark = None
    if self._infeed is not None and not self._infeed.healthy:
      self._infeed.Reset()

  def Shutdown(self) -> None:
    """Clean teardown between programs / at executor exit: best-effort
    telemetry flush, then stop the producer thread and the worker. The
    program stays usable — the next Run lazily restarts both (note any
    prefetched-but-unconsumed batches are discarded at Stop)."""
    try:
      self.Flush()
    except BaseException:  # noqa: BLE001 - already surfaced via Run/Flush
      pass
    if self._infeed is not None:
      self._infeed.Stop()
      self._infeed = None
    if self._telemetry is not None:
      self._telemetry.Shutdown()
      self._telemetry = None


class TrainProgram(BaseProgram):
  """steps_per_loop training steps per Run (ref TrainProgram:441).

  The jit'd unit is a single TrainStep; the host loop feeds batches and
  donates the state buffers so theta/opt-state update in place on device.
  """

  # flops per optimizer step, from the step executable's XLA cost
  # analysis; set once (AOT compile record or lazy first-Run lower())
  _flops_per_step: float | None = None

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "train"
    p.Define("base_step_seed", 1234, "Base PRNG seed for step seeds.")
    p.Define("on_device_loop", False,
             "Run all steps_per_loop inside ONE jit call (lax.scan over a "
             "stacked batch) — one host round-trip per loop instead of per "
             "step (ref tpu_training_loop.repeat, program.py:601-609). The "
             "host prefetches steps_per_loop batches and stacks them.")
    p.Define("defer_telemetry", True,
             "Under async_infeed, run the post-loop metric device_get + "
             "summary writes on a background worker; Run returns the most "
             "recent COMPLETED loop's result (lags dispatch by <= 1 loop). "
             "False fetches synchronously after dispatch (infeed overlap "
             "only). Ignored when async_infeed is False.")
    p.Define("pipeline_depth", 2,
             "k-deep dispatch window under async_infeed + defer_telemetry: "
             "Run may leave up to this many loops' telemetry unresolved, "
             "so loop k+1 dispatches before loop k's metrics land and the "
             "returned result is stale by at most this many loops. Also "
             "switches to host-side step tracking (no device_get of "
             "state.step between fences). 0 = the exact legacy lag-1 "
             "behavior (kill switch; docs/pipelined_executor.md).")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:
      key = jax.random.PRNGKey(self.p.base_step_seed)
      state_shardings = None
      if (self.p.mesh is not None and self.p.state_sharding_fn is not None and
          state is not None):
        state_shardings = self.p.state_sharding_fn(state)

      def _Step(state, batch):
        if state_shardings is not None:
          state = jax.lax.with_sharding_constraint(state, state_shardings)
        new_state, out = self._task.TrainStep(state, batch, key)
        if state_shardings is not None:
          new_state = jax.lax.with_sharding_constraint(new_state,
                                                       state_shardings)
        return new_state, out

      self._step_fn = jax.jit(_Step, donate_argnums=_StateDonation())
    return self._step_fn

  def Compile(self, state: NestedMap) -> None:
    if not self.p.on_device_loop:
      return super().Compile(state)
    # shapes only: tile ONE batch rather than consuming steps_per_loop
    # real batches from a possibly-finite stream
    batch = self.input_generator.GetPreprocessedInputBatch()
    stacked = batch.Transform(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x)[None], (self.p.steps_per_loop,) + np.shape(x)))
    with self._MeshScope():
      self._RecordCompile("loop", self._GetLoopFn(state), state, stacked)

  def _GetLoopFn(self, state: NestedMap | None = None):
    """steps_per_loop TrainSteps as ONE jitted lax.scan over stacked batches
    (the reference's on-device training loop, program.py:601-609)."""
    if self._loop_fn is None:

      state_shardings = None
      if (self.p.mesh is not None and self.p.state_sharding_fn is not None
          and state is not None):
        state_shardings = self.p.state_sharding_fn(state)

      def _Loop(state, stacked_batches):
        key = jax.random.PRNGKey(self.p.base_step_seed)

        def _Body(carry, batch):
          state, acc, stats_acc = carry
          if state_shardings is not None:
            state = jax.lax.with_sharding_constraint(state, state_shardings)
          state, out = self._task.TrainStep(state, batch, key)
          if state_shardings is not None:
            state = jax.lax.with_sharding_constraint(state, state_shardings)
          acc = metrics_lib.AccumulateMetrics(acc, out.metrics)
          stats = NestedMap(
              {k: (v, 1.0) for k, v in out.stats.FlattenItems()})
          stats.update(_ScalarSummaryPairs(out))
          stats_acc = metrics_lib.AccumulateMetrics(stats_acc, stats)
          return (state, acc, stats_acc), ()

        # fixed-structure zero accumulators (scan carries can't grow)
        _, out_shape = jax.eval_shape(
            lambda s, b: self._task.TrainStep(s, b, key), state,
            jax.tree_util.tree_map(lambda x: x[0], stacked_batches))
        zeros = lambda m: NestedMap(
            {k: jnp.zeros((2,), jnp.float32) for k in m.keys()})
        acc0 = zeros(out_shape.metrics)
        stats0 = NestedMap({k: jnp.zeros((2,), jnp.float32)
                            for k, _ in out_shape.stats.FlattenItems()})
        stats0.update({k: jnp.zeros((2,), jnp.float32)
                       for k in _ScalarSummaryPairs(out_shape)})
        (state, acc, stats_acc), _ = jax.lax.scan(
            _Body, (state, acc0, stats0), stacked_batches)
        return state, acc, stats_acc

      self._loop_fn = jax.jit(_Loop, donate_argnums=_StateDonation())
    return self._loop_fn

  def _PutStackedBatch(self, stacked: NestedMap) -> NestedMap:
    """[steps_per_loop, ...]-stacked host batches -> device arrays. The
    stacked leading dim is the STEPS axis: keep it unsharded and shift the
    per-step batch spec right by one."""
    if self.p.mesh is not None and self.p.input_sharding is not None:
      spec = jax.sharding.PartitionSpec(None, *self.p.input_sharding)
      sharding = jax.sharding.NamedSharding(self.p.mesh, spec)
      return stacked.Transform(
          lambda x: self._PlaceLocalShard(x, sharding, batch_dim=1))
    return stacked.Transform(jnp.asarray)

  def _MakeTrainIter(self):
    """Host batch units in exactly the order the sync path consumes them:
    stacked loop batches for on_device_loop, single batches otherwise.
    Runs on the infeed producer thread (the only generator caller once
    async Run starts)."""
    p = self.p
    gen = self.input_generator
    if p.on_device_loop:
      while True:
        batches = []
        try:
          for _ in range(p.steps_per_loop):
            batches.append(gen.GetPreprocessedInputBatch())
        except StopIteration:
          return  # partial loop at stream end: dropped (sync path raises
                  # StopIteration mid-stack and loses the same batches)
        yield jax.tree_util.tree_map(lambda *xs: np.stack(xs), *batches)
    else:
      while True:
        try:
          batch = gen.GetPreprocessedInputBatch()
        except StopIteration:
          return
        yield batch

  def _GetInfeed(self):
    if self._infeed is None:
      from lingvo_tpu.runners import infeed as infeed_lib
      p = self.p
      place = self._PutStackedBatch if p.on_device_loop else self._PutBatch
      self._infeed = infeed_lib.DeviceInfeed(
          self._MakeTrainIter, place_fn=place, depth=p.infeed_depth,
          place_in_producer=self._PlaceInProducer(),
          name=f"{p.name or 'train'}-infeed",
          stream_key=id(self.input_generator), registry=self.metrics)
    return self._infeed

  def _GetTelemetry(self):
    if self._telemetry is None:
      from lingvo_tpu.runners import infeed as infeed_lib
      self._telemetry = infeed_lib.DeferredTelemetry(
          name=f"{self.p.name or 'train'}-telemetry",
          registry=self.metrics)
    return self._telemetry

  def _OnCompileRecord(self, name: str, rec: dict) -> None:
    """Derives flops/step from the AOT compile's cost analysis and wires
    the `train/mfu` lazy gauge ("loop" compiles cover steps_per_loop
    optimizer steps in one executable)."""
    flops = rec.get("flops", 0.0)
    if flops <= 0:
      return
    steps = self.p.steps_per_loop if name == "loop" else 1
    self._SetFlopsPerStep(flops / max(steps, 1))

  def _SetFlopsPerStep(self, flops_per_step: float) -> None:
    self._flops_per_step = flops_per_step
    goodput_lib.PublishMfu(
        self.metrics, flops_per_step,
        rate_gauge=f"train/{self.p.name or 'train'}_steps_per_second")

  def _MaybePublishMfu(self, fn, *args, steps: int = 1) -> None:
    """Lazy flops/step for runs without an AOT Compile(): one abstract
    `.lower().cost_analysis()` on the first Run — tracing only, never a
    second XLA compilation (jax >= 0.4.30 analyzes the lowered HLO)."""
    if self._flops_per_step is not None or not hasattr(fn, "lower"):
      return
    try:
      cost = fn.lower(*args).cost_analysis()
      if isinstance(cost, (list, tuple)):
        cost = cost[0]
      flops = float((cost or {}).get("flops", 0.0))
    except Exception:  # noqa: BLE001 - cost analysis is backend-optional
      flops = 0.0
    if flops > 0:
      self._SetFlopsPerStep(flops / max(steps, 1))
    else:
      self._flops_per_step = 0.0   # don't re-trace every Run

  def _MarkRunStart(self) -> None:
    self._run_compile_mark = self._goodput.CompileSeconds()

  def _AttributeRunWall(self, t_start: float, infeed_wait_s: float) -> None:
    """Goodput attribution of one Run's wall: input wait is badput, the
    rest is the productive device loop minus any lazy jit compiles the
    jax.monitoring listener attributed inside the window (first Run
    without AOT precompile — that wall is compile badput, not step). In
    the async/deferred pipeline the Run blocks on the PREVIOUS loop's
    telemetry, so in steady state its wall still spans ~one device loop."""
    wall = max(time.time() - t_start, 0.0)
    compiled = max(
        self._goodput.CompileSeconds()
        - getattr(self, "_run_compile_mark", 0.0), 0.0)
    self._goodput.Add("infeed_wait", min(max(infeed_wait_s, 0.0), wall))
    self._goodput.Add("step", max(wall - infeed_wait_s - compiled, 0.0))

  def _RefreshHostSchedules(self) -> None:
    """Host-driven schedules (DevBasedSchedule anneal-on-plateau) may change
    between runs; their values are trace-time constants, so a change must
    drop the cached jitted functions (rare — a few decays per run)."""
    key = []
    for lrn in getattr(self._task, "learners", []):
      sched = getattr(lrn, "lr_sched", None)
      if sched is None:
        continue
      if hasattr(sched, "UpdateFromHistory"):
        sched.UpdateFromHistory()
      if hasattr(sched, "HostStateKey"):
        key.append(sched.HostStateKey())
    key = tuple(key)
    if key != getattr(self, "_host_sched_key", None):
      if getattr(self, "_host_sched_key", None) is not None:
        self._loop_fn = None
        self._step_fn = None
      self._host_sched_key = key

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    self._RefreshHostSchedules()
    if not self.p.async_infeed:
      return self._RunSync(state)
    return self._RunAsync(state)

  def _RunSync(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    """The legacy fully-synchronous loop (p.async_infeed = False): host
    batch prep, device loop, metric fetch and summary writes all serialize
    on this thread. Kept bit-exact as the kill-switch reference behavior;
    only the infeed_wait_s / host_overhead_s timers are new."""
    p = self.p
    t0 = time.time()
    self._MarkRunStart()
    if p.on_device_loop:
      # host: prefetch + stack steps_per_loop batches; device: one program
      t_in = time.perf_counter()
      batches = [self.input_generator.GetPreprocessedInputBatch()
                 for _ in range(p.steps_per_loop)]
      stacked = jax.tree_util.tree_map(
          lambda *xs: np.stack(xs), *batches)
      stacked = self._PutStackedBatch(stacked)
      infeed_wait_s = time.perf_counter() - t_in
      fn = self._GetLoopFn(state)
      self._MaybePublishMfu(fn, state, stacked, steps=p.steps_per_loop)
      with self._MeshScope(), self._ProfilerScope():
        state, acc, stats_acc = fn(state, stacked)
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    else:
      fn = self._GetStepFn(state)
      acc = None
      stats_acc = None
      infeed_wait_s = 0.0
      with self._MeshScope(), self._ProfilerScope():
        for _ in range(p.steps_per_loop):
          t_in = time.perf_counter()
          batch = self._PutBatch(
              self.input_generator.GetPreprocessedInputBatch())
          infeed_wait_s += time.perf_counter() - t_in
          self._MaybePublishMfu(fn, state, batch)
          state, out = fn(state, batch)
          acc = metrics_lib.AccumulateMetrics(acc, out.metrics)
          stats_pairs = NestedMap(
              {k: (v, 1.0) for k, v in out.stats.FlattenItems()})
          stats_pairs.update(_ScalarSummaryPairs(out))
          stats_acc = metrics_lib.AccumulateMetrics(stats_acc, stats_pairs)
        # One host sync per loop (ref: one session.run per steps_per_loop);
        # inside the profiler scope so traces capture the device work.
        jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    wall = time.time() - t0
    self._AttributeRunWall(t0, infeed_wait_s)
    t_tel = time.perf_counter()
    result = metrics_lib.FinalizeMetrics(acc) if acc else {}
    if stats_acc:
      result.update(metrics_lib.FinalizeMetrics(stats_acc))
    result["steps_per_second"] = p.steps_per_loop / wall
    result["examples_per_second"] = (
        p.steps_per_loop * self.input_generator.GlobalBatchSize() / wall)
    step = int(jax.device_get(state.step))
    # loop wall attribution (satellite of the async-infeed PR): input wait
    # vs host-side telemetry fetch — on this path both sit on the critical
    # path between device loops
    result["infeed_wait_s"] = round(infeed_wait_s, 6)
    result["host_overhead_s"] = round(
        infeed_wait_s + (time.perf_counter() - t_tel), 6)
    for k, v in self._InputStatsOf(self.input_generator).items():
      result[f"input_{k}"] = v
    # smoothed cross-Run rate incl. eval gaps (ref StepRateTracker:393)
    result["global_steps_per_second"] = self._rate_tracker.Update(
        step, self.input_generator.GlobalBatchSize())
    self.WriteSummaries(step, result)
    self._NotifyLoopDone()
    return state, result

  def _RunAsync(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    """Async pipeline: batches come pre-prepared (and, single-process,
    pre-placed) from the infeed producer; the post-loop metric fetch +
    summary write run on the telemetry worker. Batch order is bit-identical
    to _RunSync; the returned result is the most recent COMPLETED loop's
    (<= pipeline_depth loops stale — <= 1 for the legacy pipeline_depth=0
    window; the first Run blocks for its own)."""
    p = self.p
    t0 = time.time()
    self._MarkRunStart()
    infeed = self._GetInfeed()
    wait0 = infeed.wait_s
    pipelined = p.defer_telemetry and int(p.pipeline_depth or 0) >= 1
    if pipelined:
      if self._host_step is None:
        # the ONLY steady-path device fetch: seed host-side step tracking
        # from the concrete restored/initial state, before this Run's
        # dispatch makes `state.step` an in-flight value
        self._host_step = int(jax.device_get(state.step))
      if self._pipe_t_mark is None:
        self._pipe_t_mark = t0
        self._pipe_wait_mark = wait0
        self._pipe_compile_mark = self._goodput.CompileSeconds()
    if p.on_device_loop:
      stacked = infeed.Get()
      if stacked is None:
        raise StopIteration("train input exhausted")
      if not infeed.places_batches:
        stacked = self._PutStackedBatch(stacked)
      fn = self._GetLoopFn(state)
      self._MaybePublishMfu(fn, state, stacked, steps=p.steps_per_loop)
      with self._MeshScope(), self._ProfilerScope():
        state, acc, stats_acc = fn(state, stacked)
        if self._profiling_run:
          # opt-in diagnostics: keep the device work inside the trace
          jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    else:
      fn = self._GetStepFn(state)
      acc = None
      stats_acc = None
      with self._MeshScope(), self._ProfilerScope():
        for _ in range(p.steps_per_loop):
          batch = infeed.Get()
          if batch is None:
            raise StopIteration("train input exhausted")
          if not infeed.places_batches:
            batch = self._PutBatch(batch)
          self._MaybePublishMfu(fn, state, batch)
          state, out = fn(state, batch)
          acc = metrics_lib.AccumulateMetrics(acc, out.metrics)
          stats_pairs = NestedMap(
              {k: (v, 1.0) for k, v in out.stats.FlattenItems()})
          stats_pairs.update(_ScalarSummaryPairs(out))
          stats_acc = metrics_lib.AccumulateMetrics(stats_acc, stats_pairs)
        if self._profiling_run:
          jax.block_until_ready(jax.tree_util.tree_leaves(state)[0])
    # host-side cost of this Run (input wait + placement + dispatch);
    # everything below the dispatch is off the critical path
    host_overhead_s = time.time() - t0
    infeed_wait_s = infeed.wait_s - wait0
    queue_depth = infeed.QueueDepth()
    input_stats = self._InputStatsOf(self.input_generator)
    if pipelined:
      # host-side step tracking: the loop just dispatched WILL end at this
      # step (or fail, in which case recovery re-seeds from the device)
      self._host_step += p.steps_per_loop
      step_val: Any = self._host_step
    else:
      step_val = state.step
      if _StateDonation():
        # the NEXT Run's dispatch donates `state` (incl. .step) on
        # accelerator backends; hand the worker an independent derived array
        # so its deferred device_get can't hit a deleted buffer
        step_val = step_val + 0
    job = functools.partial(
        self._FinalizeLoop, step_val, acc, stats_acc, t0,
        host_overhead_s, infeed_wait_s, queue_depth, input_stats,
        pipelined=pipelined)
    if not p.defer_telemetry:
      result = job()[1]
      self._AttributeRunWall(t0, infeed_wait_s)
      return state, result
    fut = self._GetTelemetry().Submit(job)
    if not pipelined:
      # pipeline_depth=0 kill switch: the exact PR 5 lag-1 window
      prev, self._pending_telemetry = self._pending_telemetry, fut
      # steady state: return loop k-1's result (its fetch overlapped this
      # loop's dispatch); first Run after a Flush blocks for its own — and
      # marks it consumed so Flush won't report it a second time
      self._pending_consumed = prev is None
      result = (prev if prev is not None else fut).result()[1]
      self._AttributeRunWall(t0, infeed_wait_s)
      return state, result
    # k-deep dispatch window: sweep already-completed loops (free), then
    # apply backpressure so at most pipeline_depth loops stay unresolved.
    # Goodput attribution happens at loop completion
    # (_AttributePipelinedLoop), not here: this Run's wall is near zero in
    # steady state and says nothing about device time.
    self._pending.append(fut)
    while self._pending and self._pending[0].done():
      self._PopPending()
    while len(self._pending) > int(p.pipeline_depth):
      self._PopPending()
    if self._last_result is None:
      self._PopPending()   # very first loop (or first after recovery)
    self._last_result_consumed = True
    return state, self._last_result

  def _AttributePipelinedLoop(self) -> float:
    """Pipelined goodput attribution, run on the telemetry worker at loop
    COMPLETION: loops execute serially on device however far ahead the
    host dispatches, so completion-to-completion intervals partition the
    wall into per-loop spans. Each span minus the infeed wait and
    lazy-compile seconds that accrued inside it is productive step time.
    Replaces _AttributeRunWall on this path — with a k-deep window the
    Run wall is near zero and measures nothing. Returns the interval (the
    per-loop wall basis for rate metrics)."""
    now = time.time()
    prev_t = self._pipe_t_mark if self._pipe_t_mark is not None else now
    self._pipe_t_mark = now
    wait_now = self._infeed.wait_s if self._infeed is not None else 0.0
    wait_d = max(wait_now - self._pipe_wait_mark, 0.0)
    self._pipe_wait_mark = wait_now
    comp_now = self._goodput.CompileSeconds()
    comp_d = max(comp_now - self._pipe_compile_mark, 0.0)
    self._pipe_compile_mark = comp_now
    interval = max(now - prev_t, 1e-9)
    self._goodput.Add("infeed_wait", min(wait_d, interval))
    self._goodput.Add("step", max(interval - wait_d - comp_d, 0.0))
    return interval

  def _FinalizeLoop(self, step_val, acc, stats_acc, t_start,
                    host_overhead_s, infeed_wait_s, queue_depth,
                    input_stats, pipelined: bool = False,
                    ) -> tuple[int, dict[str, float]]:
    """Telemetry-worker job: device_get of one loop's metrics + summary
    write. The np.asarray inside FinalizeMetrics synchronizes on the loop's
    completion, so `wall` covers dispatch through device completion.
    step_val is a host int under host-side step tracking (pipelined), else
    the loop's device step counter."""
    p = self.p
    result = metrics_lib.FinalizeMetrics(acc) if acc else {}
    if stats_acc:
      result.update(metrics_lib.FinalizeMetrics(stats_acc))
    wall = max(time.time() - t_start, 1e-9)
    if pipelined:
      # dispatch->completion spans queue time behind earlier in-flight
      # loops; the completion-to-completion interval is the honest
      # per-loop wall (and feeds the goodput step bucket)
      wall = self._AttributePipelinedLoop()
    result["steps_per_second"] = p.steps_per_loop / wall
    result["examples_per_second"] = (
        p.steps_per_loop * self.input_generator.GlobalBatchSize() / wall)
    result["infeed_wait_s"] = round(infeed_wait_s, 6)
    result["host_overhead_s"] = round(host_overhead_s, 6)
    result["infeed_queue_depth"] = queue_depth
    for k, v in input_stats.items():
      result[f"input_{k}"] = v
    step = (int(step_val) if isinstance(step_val, int)
            else int(jax.device_get(step_val)))
    result["global_steps_per_second"] = self._rate_tracker.Update(
        step, self.input_generator.GlobalBatchSize())
    self.WriteSummaries(step, result)
    # stamped AFTER the summary write (the jsonl rows are keyed by step
    # already): lets executor metrics rows disambiguate the bounded lag
    result["at_step"] = step
    self._NotifyLoopDone()
    return step, result


class EvalProgram(BaseProgram):
  """Whole-dataset eval with fixed-shape metric accumulation
  (ref EvalProgram:995)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "eval"
    p.dataset_name = "Test"
    p.Define("use_ema", True, "Eval with EMA weights when available.")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:

      def _Step(theta, batch, step):
        metrics, _ = self._task.EvalStep(theta, batch, step=step)
        return metrics

      self._step_fn = jax.jit(_Step)
    return self._step_fn

  def _EvalTheta(self, state: NestedMap) -> NestedMap:
    if self.p.use_ema and "ema_theta" in state:
      return state.ema_theta
    return state.theta

  def _MaxEvalBatches(self) -> int:
    """Eval budget: task's eval.samples_per_summary wins over steps_per_loop
    (ref base_model.py eval params; 0 = unlimited for finite datasets)."""
    sps = getattr(self._task.p.eval, "samples_per_summary", 0)
    if sps:
      # each coordinated step consumes a GLOBAL batch (all hosts' shards)
      bs = max(1, self.input_generator.InfeedBatchSize()
               * jax.process_count())
      return max(1, -(-sps // bs))
    return self.p.steps_per_loop

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    with self._goodput.TrackExcludingCompile("eval"):   # badput, minus compiles
      return self._RunEval(state)

  def _RunEval(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    fn = self._GetStepFn(state)
    theta = self._EvalTheta(state)
    acc = None
    gen = self.input_generator
    max_batches = self._MaxEvalBatches()
    raw = (gen.EpochBatches() if hasattr(gen, "EpochBatches")
           else _TakeN(gen, max_batches))
    # Async infeed: prefetch (and, single-process, pre-place) eval batches
    # on a producer thread so host batch prep overlaps the device eval
    # steps. The multi-host batch-availability barrier stays on THIS thread
    # (its process_allgather must not run concurrently with the eval step's
    # collectives). One throwaway infeed per Run: eval streams are finite
    # and the generator is Reset between cycles.
    infeed = None
    if self.p.async_infeed:
      from lingvo_tpu.runners import infeed as infeed_lib
      infeed = infeed_lib.DeviceInfeed(
          lambda: raw, place_fn=self._PutBatch, depth=self.p.infeed_depth,
          place_in_producer=self._PlaceInProducer(),
          name=f"{self.p.name or 'eval'}-infeed", stream_key=id(gen),
          registry=self.metrics)
    batches = _CoordinateFiniteStream(
        infeed.Iter() if infeed is not None else raw)
    n = 0
    infeed_wait_s = 0.0
    try:
      with self._MeshScope(), self._ProfilerScope():
        for batch in batches:
          if infeed is None or not infeed.places_batches:
            batch = self._PutBatch(batch)
          out = fn(theta, batch, state.step)
          acc = metrics_lib.AccumulateMetrics(acc, out)
          n += 1
          if n >= max_batches:
            break
    finally:
      if infeed is not None:
        infeed_wait_s = infeed.wait_s
        infeed.Stop()
    result = metrics_lib.FinalizeMetrics(acc) if acc else {}
    if infeed is not None:
      result["infeed_wait_s"] = round(infeed_wait_s, 6)
    _MaybeResetFiniteStream(gen)
    step = int(jax.device_get(state.step))
    self.WriteSummaries(step, result)
    self._NotifyLoopDone()
    return state, result


class DecodeProgram(BaseProgram):
  """Device decode + host postprocess into decoder metrics
  (ref DecodeProgram:1229)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "decode"
    p.dataset_name = "Test"
    p.Define("use_ema", True, "Decode with EMA weights when available.")
    return p

  def _GetStepFn(self, state: NestedMap | None = None):
    if self._step_fn is None:

      def _Step(theta, batch):
        with py_utils.EvalContext():
          return self._task.Decode(theta, batch)

      self._step_fn = jax.jit(_Step)
    return self._step_fn

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    with self._goodput.TrackExcludingCompile("eval"):   # decode rides eval badput
      return self._RunDecode(state)

  def _RunDecode(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    fn = self._GetStepFn(state)
    theta = (state.ema_theta
             if self.p.use_ema and "ema_theta" in state else state.theta)
    dec_metrics = self._task.CreateDecoderMetrics()
    gen = self.input_generator
    batches = _CoordinateFiniteStream(
        gen.EpochBatches() if hasattr(gen, "EpochBatches")
        else _TakeN(gen, self.p.steps_per_loop))
    n = 0
    # async host postprocess (ref DecodeProgram:1487-1529): the device
    # decodes batch k+1 while ONE worker thread postprocesses batch k.
    # One outstanding future max: bounded memory (host_out trees are big)
    # and exceptions surface within one batch, while keeping the k/k+1
    # overlap. Single worker => decoder metrics mutate without locks.
    from concurrent.futures import ThreadPoolExecutor
    pending = None
    with self._MeshScope(), self._ProfilerScope(), \
         ThreadPoolExecutor(max_workers=1) as pool:
      for batch in batches:
        out = fn(theta, self._PutBatch(batch))
        if jax.process_count() > 1:
          # batch-sharded outputs are not host-addressable: gather the
          # global tree so postprocess sees every example (every process
          # computes identical metrics; only process 0 writes). Global
          # fully-replicated leaves (scalar counters, reduced statistics a
          # task adds to its Decode output) skip the collective — every
          # process already holds the value; everything else (global
          # batch-sharded arrays, host-local or numpy leaves that differ
          # per process) goes through process_allgather as before.
          from jax.experimental import multihost_utils

          def _GatherLeaf(leaf):
            if (isinstance(leaf, jax.Array)
                and not leaf.is_fully_addressable
                and leaf.is_fully_replicated):
              return np.asarray(leaf.addressable_shards[0].data)
            return multihost_utils.process_allgather(leaf, tiled=True)

          out = jax.tree_util.tree_map(_GatherLeaf, out)
        host_out = jax.tree_util.tree_map(np.asarray, out)
        if n == 0 and isinstance(host_out, NestedMap) and (
            jax.process_index() == 0):
          probs = host_out.Get("atten_probs")
          if probs is not None:
            from lingvo_tpu.core import summary_utils
            summary_utils.AddAttentionSummary(
                self._tb, f"{self.p.name}/atten", probs,
                int(jax.device_get(state.step)))
        if pending is not None:
          pending.result()  # backpressure + surface exceptions promptly
        pending = pool.submit(self._task.PostProcessDecodeOut, host_out,
                              dec_metrics)
        n += 1
        if n >= self.p.steps_per_loop:
          break
      if pending is not None:
        pending.result()
    result = self._task.DecodeFinalize(dec_metrics)
    _MaybeResetFiniteStream(gen)
    step = int(jax.device_get(state.step))
    self.WriteSummaries(step, result)
    self._NotifyLoopDone()
    return state, result


class InputBenchmarkProgram(BaseProgram):
  """Measures input-pipeline throughput without touching the model (ref
  `InputBenchmark:2249`): drains steps_per_loop batches from the generator
  and reports batches/sec + examples/sec."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.name = "input_benchmark"
    p.Define("warmup_batches", 2, "Batches drawn before timing starts.")
    return p

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, float]]:
    gen = self.input_generator
    for _ in range(self.p.warmup_batches):
      gen.GetPreprocessedInputBatch()
    t0 = time.time()
    n = examples = 0
    for _ in range(self.p.steps_per_loop):
      batch = gen.GetPreprocessedInputBatch()
      batched = [l for l in batch.Flatten() if np.ndim(l) >= 1]
      examples += int(batched[0].shape[0]) if batched else 0
      n += 1
    wall = max(time.time() - t0, 1e-9)
    result = {
        "batches_per_second": n / wall,
        "examples_per_second": examples / wall,
    }
    step = int(jax.device_get(state.step)) if hasattr(state, "step") else 0
    self.WriteSummaries(step, result)
    return state, result


def PlaceStateForPrograms(programs, state):
  """Places (or, for an abstract template, annotates) a train state onto
  the mesh shardings of whichever program declares them.

  Multi-host REQUIRES this before any collective orbax restore/save or
  mesh-spanning jit: host-local SingleDeviceSharding state is rejected.
  Works for any schedule shape — scans the given programs rather than
  assuming a single train program.
  """
  shardings = None
  for prog in programs:
    pp = prog.p if hasattr(prog, "p") else prog
    try:
      mesh_ = pp.mesh
      fn = pp.state_sharding_fn
    except (AttributeError, KeyError):
      continue  # program stub without mesh params (tests, custom runners)
    if mesh_ is not None and fn is not None:
      shardings = fn(state)
      break
  if shardings is None:
    return state
  leaves = jax.tree_util.tree_leaves(state)
  if leaves and isinstance(leaves[0], jax.ShapeDtypeStruct):
    return jax.tree_util.tree_map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        state, shardings)
  return jax.device_put(state, shardings)


def _MaybeResetFiniteStream(gen):
  """Finite (max_epochs-bounded) file streams must be re-read from the start
  on the next eval round (ref EvalProgram infeed-until-OutOfRange re-setup,
  `program.py:995`); infinite streams keep their position."""
  if getattr(getattr(gen, "p", None), "max_epochs", 0):
    gen.Reset()


def _TakeN(gen, n):
  it = iter(gen)
  for _ in range(n):
    try:
      yield next(it)
    except StopIteration:
      return


def _CoordinateFiniteStream(batches):
  """Multi-host barrier on batch availability: hosts with disjoint finite
  input shards can yield UNEQUAL batch counts; since every program step is
  a cross-process collective, a host iterating one batch more than another
  deadlocks. Stops ALL hosts as soon as ANY host runs dry (the tail
  examples on longer shards are skipped — the price of collective eval;
  ref the infeed-until-OutOfRange coordination in program.py:1386)."""
  if jax.process_count() <= 1:
    yield from batches
    return
  from jax.experimental import multihost_utils
  it = iter(batches)
  while True:
    try:
      batch = next(it)
      have = True
    except StopIteration:
      batch = None
      have = False
    counts = multihost_utils.process_allgather(
        np.asarray([1 if have else 0]))
    if not bool(np.all(counts)):
      return
    yield batch


class SimpleProgramSchedule:
  """Train K loops, then run eval/decode programs
  (ref SimpleProgramSchedule:2329)."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "schedule", "Name.")
    p.Define("train_program", None, "TrainProgram params (or None).")
    p.Define("eval_programs", [], "List of eval/decode program params.")
    p.Define("train_executions_per_eval", 1,
             "Train Run() calls between eval rounds.")
    return p

  def __init__(self, params, task=None, input_generators=None):
    self.p = params.Copy()
    input_generators = input_generators or {}
    self.train_program = None
    if self.p.train_program is not None:
      self.train_program = self.p.train_program.cls(
          self.p.train_program, task=task,
          input_generator=input_generators.get(
              self.p.train_program.dataset_name))
    self.eval_programs = [
        ep.cls(ep, task=task,
               input_generator=input_generators.get(ep.dataset_name))
        for ep in self.p.eval_programs
    ]

  @property
  def programs(self):
    out = []
    if self.train_program:
      out.append(self.train_program)
    return out + list(self.eval_programs)

  def StepsPerCycle(self) -> int:
    """Optimizer steps one Run() advances the train state by — the
    executor's host-side step arithmetic (pipelined main loop) relies on
    this being deterministic. 0 = no train program (the executor falls
    back to device-step fetching). Schedules without this method (e.g.
    MultiTaskProgramSchedule, whose per-cycle step count depends on the
    sampled task) are never pipelined."""
    if self.train_program is None:
      return 0
    return (max(1, self.p.train_executions_per_eval)
            * int(self.train_program.p.steps_per_loop))

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, Any]]:
    results: dict[str, Any] = {}
    if self.train_program is not None:
      train_result = None
      for _ in range(max(1, self.p.train_executions_per_eval)):
        state, train_result = self.train_program.Run(state)
      results["train"] = train_result
      if self.eval_programs:
        # program boundary: land the deferred telemetry of the last train
        # loop before eval starts (summary ordering), and report the
        # CURRENT loop's result to the executor instead of the lagged one
        flushed = self.train_program.Flush()
        if flushed is not None:
          results["train"] = flushed
    for ep in self.eval_programs:
      state, r = ep.Run(state)
      results[ep.p.name] = r
    return state, results


class MultiTaskProgramSchedule:
  """Per-task train programs driven by a sampling TaskScheduler.

  The executor-side expansion of a MultiTaskModel (ref
  `executor.py:67-153` GetExecutorParams + the per-cycle
  `task_scheduler.Sample` at `executor.py:573`, and `SampleTask` in
  `base_model.py:1480`): each cycle samples one task name and runs that
  task's TrainProgram for its steps_per_loop. The combined train state is
  NestedMap(tasks={name: per-task state}, step=total steps) so a single
  checkpointer handles save/restore for the whole model.
  """

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", "multitask_schedule", "Name.")
    p.Define("task_schedule", None, "TaskScheduler params.")
    p.Define("train_programs", None,
             "Params holding one TrainProgram params per task name.")
    p.Define("eval_programs", [], "Eval/decode program params (any task).")
    p.Define("train_executions_per_eval", 1,
             "Train cycles between eval rounds (ref "
             "SimpleProgramSchedule.train_executions_per_eval).")
    p.Define("variable_renaming_rules", [],
             "[(regex, replacement)] over dotted theta paths; tasks whose renamed "
             "paths collide share those variables (ref multitask_model.py "
             "RegExSharedVariableModel). Shared values are unified at init "
             "and propagated from the sampled task after each train cycle.")
    return p

  def __init__(self, params, tasks: dict | None = None,
               input_generators: dict | None = None, task=None):
    """tasks: {task_name: task instance} (instantiated from each train
    program's task params when omitted — the trainer CLI path);
    input_generators: {(task_name, dataset_name): generator}, or
    {dataset_name: generator} applied to every task. `task` is accepted for
    SimpleProgramSchedule constructor compatibility and ignored when `tasks`
    is given."""
    del task  # the multi-task schedule owns its task set
    self.p = params.Copy()
    input_generators = input_generators or {}
    if tasks is None:
      tasks = {}
      for name, tp in self.p.train_programs.IterParams():
        tasks[name] = tp.task.Instantiate()
        tasks[name].FinalizePaths()
    self._tasks = dict(tasks)
    self._scheduler = self.p.task_schedule.Instantiate()
    self._runs_since_eval = 0
    self._shared_rules = None
    if self.p.variable_renaming_rules:
      from lingvo_tpu.core import multitask_model
      self._shared_rules = multitask_model.SharedVariableRules(
          self.p.variable_renaming_rules)

    def _GenFor(name, dataset):
      if (name, dataset) in input_generators:
        return input_generators[(name, dataset)]
      return input_generators.get(dataset)

    self.train_programs = {}
    for name, tp in self.p.train_programs.IterParams():
      self.train_programs[name] = tp.cls(
          tp, task=tasks[name],
          input_generator=_GenFor(name, tp.dataset_name))
    self.eval_programs = []
    for ep in self.p.eval_programs:
      task_name = getattr(ep, "task_name", None) or next(iter(tasks))
      self.eval_programs.append(
          ep.cls(ep, task=tasks[task_name],
                 input_generator=_GenFor(task_name, ep.dataset_name)))

  @property
  def programs(self):
    return list(self.train_programs.values()) + list(self.eval_programs)

  @property
  def tasks(self):
    return dict(self._tasks)

  def CreateTrainState(self, key) -> NestedMap:
    import jax
    states = NestedMap()
    keys = jax.random.split(key, len(self._tasks))
    for k, name in zip(keys, sorted(self._tasks)):
      states.Set(name, self._tasks[name].CreateTrainState(k))
    if self._shared_rules is not None:
      states = self._shared_rules.UnifyStates(states)
    return NestedMap(tasks=states, step=jnp.zeros((), jnp.int32))

  def Run(self, state: NestedMap) -> tuple[NestedMap, dict[str, Any]]:
    import jax
    total_step = int(jax.device_get(state.step))
    name = self._scheduler.Sample(total_step)
    task_state = state.tasks.GetItem(name)
    task_state, result = self.train_programs[name].Run(task_state)
    state.tasks.Set(name, task_state)
    if self._shared_rules is not None:
      state.tasks = self._shared_rules.Propagate(state.tasks, name)
    state.step = jnp.asarray(
        sum(int(jax.device_get(state.tasks.GetItem(n).step))
            for n in sorted(self._tasks)), jnp.int32)
    results = {f"train_{name}": result, "sampled_task": name}
    self._runs_since_eval += 1
    if self._runs_since_eval >= max(1, self.p.train_executions_per_eval):
      self._runs_since_eval = 0
      if self.eval_programs:
        # program boundary: see SimpleProgramSchedule.Run
        flushed = self.train_programs[name].Flush()
        if flushed is not None:
          results[f"train_{name}"] = flushed
      for ep in self.eval_programs:
        task_name = (getattr(ep.p, "task_name", None)
                     or next(iter(self._tasks)))
        st, r = ep.Run(state.tasks.GetItem(task_name))
        state.tasks.Set(task_name, st)
        results[ep.p.name] = r
    return state, results
