"""Async device infeed + deferred telemetry for program host loops.

Re-designs the reference's L2 input machinery (`CreateTpuEnqueueOps`,
`base_input_generator.py:446`): there, host->device enqueue is double-buffered
against device dequeue so the accelerator never waits on input, and outfeed /
summary fetch runs on separate threads. In the JAX stack the device loop is a
jitted program fed by `device_put` batches, so the equivalent overlap is:

- `DeviceInfeed`: ONE background producer thread pulls host batches from the
  input generator (and optionally places them under the input sharding) into
  a bounded FIFO queue while the device computes the previous loop. A single
  producer + FIFO means the consumed batch sequence is bit-identical to
  calling the generator inline.
- `DeferredTelemetry`: ONE background worker runs the post-loop
  `device_get` of metrics/stats and the summary writes, so host fetch never
  sits between two device loops. Jobs run in submission order (single
  worker), keeping summaries ordered and the step-rate tracker monotone.

Producer/worker exceptions are latched and re-raised at the consumer
(`Get()` / `Future.result()`), so the train loop — and the executor's
transient-retry path above it — sees the real error instead of a silent
end-of-data or a dropped summary.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Iterator

_EOS = object()  # end-of-stream sentinel (never a valid batch)

# Producer threads that outlived their Stop() join (blocked inside the input
# generator), keyed by input stream: a NEW producer over the same stream —
# including one from a fresh DeviceInfeed instance (eval creates a throwaway
# infeed per Run) — must wait these out or fail loudly rather than race the
# generator and corrupt batch order.
_LINGERING_LOCK = threading.Lock()
_LINGERING: dict[Any, threading.Thread] = {}

# One-shot multi-host producer-placement probe verdict (see
# ProbeProducerPlacement). Cached per process: the answer is a property of
# the runtime/backend pairing, not of any one program.
_PROBE_LOCK = threading.Lock()
_PROBE_VERDICT: bool | None = None


def _DefaultPlacementProbe() -> None:
  """Representative off-main-thread `make_array_from_process_local_data`
  call: a tiny replicated array over every device. Raises (or hangs) on
  runtimes where the collective array build is not thread-safe off the
  main thread."""
  import jax
  import numpy as np
  devs = np.asarray(jax.devices())
  mesh = jax.sharding.Mesh(devs.reshape(-1), ("probe",))
  sharding = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
  arr = jax.make_array_from_process_local_data(
      sharding, np.zeros((1,), np.float32), (1,))
  jax.block_until_ready(arr)


def ProbeProducerPlacement(probe_fn: Callable[[], None] | None = None,
                           timeout_s: float = 20.0) -> bool:
  """One-shot safety probe: may H2D placement run on the producer thread
  under real multi-host?

  Producer-side placement overlaps the H2D transfer with compute, but
  `jax.make_array_from_process_local_data` builds a *global* array and some
  runtime versions only support that from the main thread. Rather than
  hard-coding the conservative consumer-side fallback forever, run ONE
  representative call on a scratch thread with a join timeout; any
  exception or hang means "not safe". Multi-process, the verdict is
  all-reduced (process_allgather on the calling thread) so every host makes
  the same producer-vs-consumer placement choice — hosts disagreeing would
  skew per-host infeed latency and, worse, diverge any placement-dependent
  collective setup.

  The default probe's verdict is cached for the process; an injected
  `probe_fn` (tests) bypasses the cache.
  """
  global _PROBE_VERDICT
  import jax
  with _PROBE_LOCK:
    if probe_fn is None and _PROBE_VERDICT is not None:
      return _PROBE_VERDICT
    ok = [False]

    def _Run():
      try:
        (probe_fn or _DefaultPlacementProbe)()
        ok[0] = True
      except BaseException:  # noqa: BLE001 - any failure means "not safe"
        ok[0] = False

    t = threading.Thread(target=_Run, daemon=True, name="placement-probe")
    t.start()
    t.join(timeout_s)
    verdict = bool(ok[0]) and not t.is_alive()
    if jax.process_count() > 1:
      try:
        import numpy as np
        from jax.experimental import multihost_utils
        verdicts = multihost_utils.process_allgather(np.asarray([verdict]))
        verdict = bool(np.all(verdicts))
      except BaseException:  # noqa: BLE001 - coordination failure: fall back
        verdict = False
    if probe_fn is None:
      _PROBE_VERDICT = verdict
    return verdict


class DeviceInfeed:
  """Bounded background producer queue feeding device (or host) batches.

  Args:
    make_iter: callable returning a FRESH iterator of host batches; invoked
      once per producer start (and again after `Reset`).
    place_fn: optional host->device placement applied per batch.
    depth: queue capacity (loop batches for on-device loops, single batches
      for per-step loops) — bounds host memory while the device lags.
    place_in_producer: apply `place_fn` on the producer thread so the H2D
      transfer overlaps compute too. False hands numpy to the consumer,
      which must place — the verified-safe multi-process variant, keeping
      `make_array_from_process_local_data` on the consumer thread.
    name: thread-name prefix for debugging.
    stream_key: identity of the underlying input stream (e.g.
      `id(generator)`). Serializes producers across DeviceInfeed
      *instances* sharing one stream — see _LINGERING. Defaults to this
      instance (per-instance protection only).
    registry: optional observe.MetricsRegistry — registers an
      `infeed/<name>` section (wait_s / batches / queue_depth / healthy)
      so every live infeed is visible in one snapshot; re-registering
      under the same name replaces the section (throwaway eval infeeds).

  Batch ORDER is the iterator's order: one producer thread and one FIFO
  queue, so the consumed sequence is bit-identical to the synchronous path.
  """

  def __init__(self, make_iter: Callable[[], Iterator[Any]],
               place_fn: Callable[[Any], Any] | None = None,
               depth: int = 2, place_in_producer: bool = True,
               name: str = "infeed", stream_key: Any = None,
               registry: Any = None):
    self._stream_key = stream_key if stream_key is not None else id(self)
    self._make_iter = make_iter
    self._place_fn = place_fn
    self._depth = max(1, int(depth))
    self._place_in_producer = bool(place_in_producer and
                                   place_fn is not None)
    self._name = name
    self._thread: threading.Thread | None = None
    self._queue: "queue.Queue" | None = None
    self._stop: threading.Event | None = None
    self._error: BaseException | None = None
    self._done = False
    self.wait_s = 0.0  # cumulative consumer blocking time (starvation)
    self.batches = 0   # batches handed to the consumer
    if registry is not None:
      registry.SectionFn(f"infeed/{name}", self.Stats)

  def Stats(self) -> dict:
    """Live counters for the registry's `infeed/<name>` section."""
    return {
        "wait_s": self.wait_s,
        "batches": self.batches,
        "queue_depth": self.QueueDepth(),
        "healthy": self.healthy,
    }

  @property
  def places_batches(self) -> bool:
    """True when Get() returns device-placed batches (skip _PutBatch)."""
    return self._place_in_producer

  @property
  def healthy(self) -> bool:
    return self._error is None

  def QueueDepth(self) -> int:
    q = self._queue
    return q.qsize() if q is not None else 0

  def _EnsureStarted(self) -> None:
    if self._thread is not None or self._done:
      return
    with _LINGERING_LOCK:
      lingering = _LINGERING.pop(self._stream_key, None)
    if lingering is not None and lingering.is_alive():
      # a previous Stop() (possibly on a DISCARDED DeviceInfeed over the
      # same stream) timed out while its producer was blocked inside the
      # generator; two producers pulling one generator would race and
      # break batch order — wait it out (it parks after its current pull)
      # or fail loudly rather than corrupt the stream
      lingering.join(timeout=30.0)
      if lingering.is_alive():
        with _LINGERING_LOCK:
          _LINGERING[self._stream_key] = lingering
        raise RuntimeError(
            f"{self._name}: previous producer thread is still blocked in "
            "the input generator; refusing to start a second producer "
            "over the same stream")
    self._queue = queue.Queue(maxsize=self._depth)
    self._stop = threading.Event()
    self._thread = threading.Thread(
        target=self._Produce, args=(self._queue, self._stop),
        name=f"{self._name}-producer", daemon=True)
    self._thread.start()

  def _Produce(self, q: "queue.Queue", stop: threading.Event) -> None:
    # q/stop passed as args (not read from self): a Reset() from the
    # consumer swaps the members, and an abandoned producer must keep
    # honoring ITS stop event rather than the replacement's.
    try:
      for item in self._make_iter():
        if self._place_in_producer:
          item = self._place_fn(item)
        while not stop.is_set():
          try:
            q.put(item, timeout=0.2)
            break
          except queue.Full:
            continue
        if stop.is_set():
          return
    except BaseException as e:  # noqa: BLE001 - surfaced at Get()
      if not stop.is_set():
        # a stopped producer's late exception must not poison the latch a
        # Reset() just cleared for the NEXT epoch
        self._error = e
    finally:
      while not stop.is_set():
        try:
          q.put(_EOS, timeout=0.2)
          return
        except queue.Full:
          continue

  def Get(self) -> Any | None:
    """Next batch, or None at end-of-stream (latched).

    Re-raises a producer exception (also latched: a dead producer must not
    masquerade as end-of-data). Blocking time accumulates in `wait_s`.
    """
    self._EnsureStarted()
    if self._done:
      if self._error is not None:
        raise self._error
      return None
    t0 = time.perf_counter()
    item = self._queue.get()
    self.wait_s += time.perf_counter() - t0
    if item is _EOS:
      self._done = True
      if self._error is not None:
        raise self._error
      return None
    self.batches += 1
    return item

  def Iter(self) -> Iterator[Any]:
    """Generator view over Get() (finite-stream consumers, e.g. eval)."""
    while True:
      item = self.Get()
      if item is None:
        return
      yield item

  def Stop(self) -> None:
    """Stops the producer and discards queued batches. Safe to call twice."""
    thread, q, stop = self._thread, self._queue, self._stop
    self._thread = None
    self._queue = None
    self._stop = None
    if stop is not None:
      stop.set()
    if q is not None:
      try:
        while True:
          q.get_nowait()
      except queue.Empty:
        pass
    if thread is not None:
      # The producer may be blocked inside the generator itself (e.g. an
      # upstream prefetcher); it is a daemon and parks after its current
      # pull, so don't hang the trainer on it here — but remember it, so a
      # restart can't race it on the same generator (_EnsureStarted).
      thread.join(timeout=5.0)
      if thread.is_alive():
        with _LINGERING_LOCK:
          _LINGERING[self._stream_key] = thread

  def Reset(self) -> None:
    """Stop + clear latched end/error state; the next Get() starts a fresh
    `make_iter()` iterator. Prefetched-but-unconsumed batches are discarded
    (callers resetting the underlying generator get a consistent restart)."""
    self.Stop()
    self._done = False
    self._error = None


class DeferredTelemetry:
  """Single-worker executor for post-loop metric fetch + summary writes.

  One worker => jobs complete in submission order. The consumer bounds the
  in-flight window (`TrainProgram.Run` keeps at most `pipeline_depth`
  unresolved loops, one for the legacy `pipeline_depth=0` path), so
  results the executor consumes — NaN-stop, trial reporting, early-stop —
  lag dispatch by at most that many loops (docs/pipelined_executor.md).
  """

  def __init__(self, name: str = "telemetry", registry: Any = None):
    self._name = name
    self._pool: ThreadPoolExecutor | None = None
    # optional job counter: how many deferred fetch/write jobs ran
    self._jobs = (registry.Counter(f"infeed/{name}_jobs")
                  if registry is not None else None)

  def Submit(self, fn: Callable[[], Any]) -> Future:
    if self._pool is None:
      self._pool = ThreadPoolExecutor(max_workers=1,
                                      thread_name_prefix=self._name)
    if self._jobs is not None:
      self._jobs.Inc()
    return self._pool.submit(fn)

  def Shutdown(self) -> None:
    """Waits for in-flight jobs; the next Submit() lazily restarts."""
    pool, self._pool = self._pool, None
    if pool is not None:
      pool.shutdown(wait=True)
