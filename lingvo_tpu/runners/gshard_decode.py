"""GShard decode driver: checkpoint-watching streaming LM decode service.

Re-designs `lingvo/gshard_decode.py` (`GShardDecode:100`): a standalone job
that watches a trainer's checkpoint directory and, for every new checkpoint,
runs prompt continuations through the LM and streams results to JSONL. The
reference's infinite-infeed/outfeed-thread machinery collapses into a jitted
sampler plus the shared checkpoint-polling loop.

Decode fast path (docs/decode_fast_path.md):
- **Chunked prefill** — the prompt primes the KV cache through
  `task.Prefill` (one full-attention pass per chunk, K/V for the whole
  chunk written in one dynamic_update_slice) instead of an O(prompt_len)
  `lax.scan` of single-token ExtendSteps. `prefill_chunk_size=0` takes the
  whole prompt in one pass; `use_legacy_prime=True` keeps the old scan
  (A/B harness for tests and bench).
- **Donated decode state** — the KV cache is built by a jitted init
  program and donated into the decode program, so the multi-megabyte
  cache buffers update in place instead of being copied at the jit
  boundary.
- **Shape bucketing** — decode programs are specialized on the static
  `(prompt_len, t_max)` pair; rounding `prompt_len` up to `len_buckets`
  makes repeat traffic with ragged prompt widths hit the jit cache instead
  of recompiling (`t_max` is a constructor constant and needs no
  bucketing). Left-pad slots added by bucketing are masked through
  `cache_paddings` exactly like ragged-prompt padding, and rotary
  attention depends only on relative position, so bucketed numerics match
  unbucketed.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu import observe
from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import sampling
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.observe import schema as observe_schema
from lingvo_tpu.quant import kv as kv_quant

# Decode-program shape buckets (slots, ascending). Lengths beyond the last
# bucket run at their exact size (a compile per distinct length).
DEFAULT_LEN_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)


class GShardDecode:
  """Streams LM samples for a fixed prompt set on every new checkpoint."""

  def __init__(self, task, train_dir: str, output_path: str,
               max_decode_steps: int = 32, temperature: float = 0.0,
               top_k: int = 0,
               poll_interval_secs: float = 10.0,
               timeout_secs: float = 3600.0,
               init_seed: int = 1234,
               prefill_chunk_size: int = 0,
               use_legacy_prime: bool = False,
               serve_int8_weights: bool = False,
               len_buckets=DEFAULT_LEN_BUCKETS,
               serve_port=None):
    """task: a TransformerLm-style task exposing InitDecodeState/ExtendStep.

    temperature/top_k: sampling controls (core/sampling.py). temperature
    <= 0 is greedy argmax — bitwise the pre-sampling behavior; top_k > 0
    restricts temperature sampling to the k largest logits. Sampling is
    seeded per request: row i draws from fold_in(step_key, i), so a
    request's continuation doesn't depend on its batch neighbors.
    prefill_chunk_size: prompt tokens per prefill attention pass (0 = the
    whole prompt in one pass). use_legacy_prime: prime the cache with the
    per-token ExtendStep scan instead of chunked prefill (slow; kept as
    the A/B reference). serve_int8_weights: rewrite each restored theta so
    decode projections run int8 integer matmuls (quant.weights — rewritten
    once per checkpoint, cached). len_buckets: prompt-width buckets.
    serve_port: when not None, a StatusServer over this driver's registry
    serves /metrics and /statusz (0 = ephemeral; read
    `self.status_server.port`); /statusz `stats` carries the last
    DecodeOnce telemetry.
    """
    self._task = task
    self._train_dir = train_dir
    self._output_path = output_path
    self._max_steps = max_decode_steps
    self._temperature = temperature
    self._top_k = top_k
    self._checkpointer = checkpointer_lib.Checkpointer(train_dir)
    self._poll_interval = poll_interval_secs
    self._timeout = timeout_secs
    self._last_step = -1
    self._prefill_chunk = prefill_chunk_size
    self._use_legacy_prime = use_legacy_prime
    self._serve_int8_weights = bool(serve_int8_weights)
    # (checkpoint step, rewritten theta) — int8 rewrite runs once per
    # restored checkpoint, not once per DecodeOnce call
    self._int8_theta = None
    self._len_buckets = tuple(len_buckets)
    self._template = jax.eval_shape(
        self._task.CreateTrainState, jax.random.PRNGKey(init_seed))
    # jitted (init_fn, prefill_fn, sample_fn) per bucketed static
    # (p_len, t_max)
    self._decode_fns = {}
    # per-call timing of the last DecodeOnce (also attached to every
    # result rec under "telemetry"): prefill_s / decode_s / total_s /
    # tokens_per_sec — the apples-to-apples numbers the serving-engine
    # bench compares against. The dict itself is a VIEW over this driver's
    # metrics registry, generated from observe.schema.GSHARD_TELEMETRY_KEYS
    # so the two serving surfaces cannot drift apart again.
    self.metrics = observe.MetricsRegistry("gshard_decode")
    self._decodes = self.metrics.Counter("serving/decodes")
    self._last_telemetry = None
    self.status_server = None
    if serve_port is not None:
      self.status_server = observe.StatusServer(
          serve_port, registry=self.metrics, name="gshard_decode",
          statusz_fn=lambda: {"telemetry": self._last_telemetry}).Start()

  def _GetDecodeFn(self, p_len: int, t_max: int):
    """Builds (init_fn, decode_fn) for a static (p_len, t_max) pair."""
    cache_key = (p_len, t_max)
    if cache_key in self._decode_fns:
      return self._decode_fns[cache_key]
    task = self._task
    temp = self._temperature
    top_k = self._top_k
    total = p_len + t_max
    chunk = self._prefill_chunk if self._prefill_chunk > 0 else p_len
    legacy_prime = self._use_legacy_prime

    def _Init(theta, batch_size):
      return task.InitDecodeState(theta, batch_size, total)

    def _CachePaddings(prompt_lens):
      # slot s is pad for row i iff s < P - len_i
      slot = jnp.arange(total)[None, :]
      return (slot < (p_len - prompt_lens)[:, None]).astype(
          jnp.float32)                                     # [B, total]

    def _Prefill(theta, prompts, prompt_lens, states):
      """prompts [B, P] RIGHT-ALIGNED (left-padded) -> (last_logits [B, V],
      primed states).

      Variable-length support: each row's prompt occupies cache slots
      [P - len_i, P), so every row's last prompt token sits at slot P-1 and
      sampling starts at slot P for all rows. Left-pad slots are excluded
      from attention forever via cache_paddings (their K/V are garbage).
      Rotary attention depends only on relative positions, so global slot
      indices give the same numerics as an unpadded per-length batch.
      """
      cache_paddings = _CachePaddings(prompt_lens)
      if legacy_prime:
        # teacher-force the prompt one token at a time (O(p_len) sequential
        # full-cache attention calls; the pre-fast-path behavior)
        def _Prime(carry, ids_t):
          states = carry
          logits, states = task.ExtendStep(theta, ids_t[:, None], states,
                                           cache_paddings=cache_paddings)
          return states, logits

        states, logits = jax.lax.scan(_Prime, states,
                                      prompts.swapaxes(0, 1))
        return logits[-1], states                          # [B, V]
      # chunked prefill: ceil(p_len / chunk) attention passes write the
      # whole prompt's K/V and produce the last-position logits; each
      # pass reads only the written cache prefix (live_len), not the
      # max_len decode tail
      chunk_logits = None
      for start in range(0, p_len, chunk):
        ids_c = prompts[:, start:start + chunk]
        chunk_logits, states = task.Prefill(
            theta, ids_c, states, cache_paddings=cache_paddings,
            live_len=start + ids_c.shape[1])
      return chunk_logits[:, -1, :], states                # [B, V]

    def _SampleLoop(theta, last_logits, prompt_lens, key, states):
      """Greedy/temperature sampling scan -> continuations [B, t_max]."""
      cache_paddings = _CachePaddings(prompt_lens)
      # per-request streams: row i folds its row index into the step key,
      # so a row's draws are a function of (checkpoint key, row, step)
      # only — not of how many neighbors share the batch
      row_seeds = jnp.arange(last_logits.shape[0], dtype=jnp.int32)

      def _Sample(carry, key_t):
        states, logits = carry
        nxt = sampling.SampleFromLogits(logits, key_t, temperature=temp,
                                        top_k=top_k, row_seeds=row_seeds)
        new_logits, states = task.ExtendStep(theta, nxt[:, None], states,
                                             cache_paddings=cache_paddings)
        return (states, new_logits), nxt

      keys = jax.random.split(key, t_max)
      _, out_ids = jax.lax.scan(_Sample, (states, last_logits), keys)
      return out_ids.swapaxes(0, 1)                        # [B, t_max]

    # the KV cache is donated through BOTH jit boundaries: the prefill
    # program reuses the init program's buffers in place and the sample
    # program reuses the prefill program's, instead of copying at each
    # boundary (XLA:CPU can't alias these buffers and warns, so donate
    # off-cpu only). The prefill/sample split (vs the old fused _Decode)
    # exists for per-phase telemetry: DecodeOnce times each program
    # separately so prefill_s/decode_s in the result dict are real
    # device-time measurements, not estimates.
    on_cpu = jax.default_backend() == "cpu"
    fns = (jax.jit(_Init, static_argnums=(1,)),
           jax.jit(_Prefill, donate_argnums=() if on_cpu else (3,)),
           jax.jit(_SampleLoop, donate_argnums=() if on_cpu else (4,)))
    self._decode_fns[cache_key] = fns
    return fns

  @staticmethod
  def _RightAlign(prompts: np.ndarray, prompt_lens: np.ndarray,
                  width: int | None = None) -> np.ndarray:
    """Shifts each row's first len_i tokens to the row's END (left-pad).

    width: output row width (>= prompts.shape[1]; defaults to it) — the
    bucketed prompt width, with bucketing pad folded into the left-pad.
    """
    prompts = np.asarray(prompts)
    p = prompts.shape[1]
    w = p if width is None else int(width)
    assert w >= p, (w, p)
    out = np.zeros((prompts.shape[0], w), prompts.dtype)
    lens = np.asarray(prompt_lens)
    if lens.shape[0] != prompts.shape[0] or (lens < 0).any() or (
        lens > p).any():
      rng = f"[{lens.min()}, {lens.max()}]" if lens.size else "[]"
      raise ValueError(
          f"prompt_lens must be [batch={prompts.shape[0]}] with values in "
          f"[0, {p}]; got shape {lens.shape}, values in {rng}")
    for i, ln in enumerate(lens):
      ln = int(ln)
      out[i, w - ln:] = prompts[i, :ln]
    return out

  def DecodeOnce(self, step: int, prompts: np.ndarray,
                 prompt_lens: np.ndarray) -> list:
    state, restored = self._checkpointer.Restore(self._template, step=step)
    theta = state.theta
    if self._serve_int8_weights:
      if self._int8_theta is None or self._int8_theta[0] != restored:
        from lingvo_tpu.quant import weights as quant_weights
        self._int8_theta = (
            restored, quant_weights.Int8ServingTheta(theta)[0])
      theta = self._int8_theta[1]
    if prompts.shape[1] == 0:
      raise ValueError("prompts must have width >= 1 (got [B, 0]); the "
                       "prefill loop needs at least one chunk")
    # only p_len varies across calls; max_steps is a constructor constant,
    # so bucketing it would just run extra discarded decode steps
    p_len = py_utils.RoundUpToBucket(prompts.shape[1], self._len_buckets)
    init_fn, prefill_fn, sample_fn = self._GetDecodeFn(p_len, self._max_steps)
    aligned = self._RightAlign(prompts, prompt_lens, width=p_len)
    states = init_fn(theta, prompts.shape[0])
    jax.block_until_ready(states)
    # measured BEFORE donation (shape metadata only): total decode-state
    # HBM per sequence — KV caches grow with p_len + max_steps, O(1) SSM
    # mixer states don't, so this is the number the mixer bench sweeps
    state_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(states)
        if hasattr(x, "nbytes"))
    lens_dev = jnp.asarray(prompt_lens)
    # per-phase wall timing (block_until_ready fences async dispatch so
    # each phase's time is its own, not its predecessor's flush)
    t0 = time.perf_counter()
    last_logits, states = prefill_fn(theta, jnp.asarray(aligned),
                                     lens_dev, states)
    jax.block_until_ready(last_logits)
    t1 = time.perf_counter()
    out = sample_fn(theta, last_logits, lens_dev,
                    jax.random.PRNGKey(restored), states)
    out = jax.block_until_ready(out)
    t2 = time.perf_counter()
    self._last_step = restored
    b = prompts.shape[0]
    decode_s = t2 - t1
    # KV-cache telemetry: the same visibility contract the serving engine's
    # Stats() carries — a quantized (or non-default-dtype) cache is never
    # silent. Non-LM tasks without a recognizable stack report None/0.
    census = kv_quant.StackKvCensus(self._task) or {}
    observe_schema.PublishTelemetry(self.metrics, observe_schema.GShardTelemetry(
        prefill_s=t1 - t0,
        decode_s=decode_s,
        total_s=t2 - t0,
        prompt_tokens=int(np.sum(prompt_lens)),
        decode_tokens=b * self._max_steps,
        tokens_per_sec=(b * self._max_steps / decode_s
                        if decode_s > 0 else 0.0),
        decode_state_bytes_per_seq=state_bytes // b,
        kv_cache_dtype=census.get("kv_cache_dtype"),
        kv_bytes_per_token=census.get("kv_bytes_per_token", 0),
        serve_int8_weights=self._serve_int8_weights,
        # speculative-decoding acceptance telemetry, mirrored with the
        # serving engine's Stats() key-set so bench comparisons line up;
        # batch-synchronous decode never drafts, so always zeros here
        draft_tokens=0,
        accepted_tokens=0,
        accepted_len_hist=[],
        spec_branches=0,
        spec_width_clamps=0,
        accepted_depth_hist=[],
        # prefix-cache telemetry, same mirroring contract: the batch-
        # synchronous driver re-prefills every prompt, so no cache exists
        prefix_hit_tokens=0,
        prefix_cache=observe_schema.DisabledPrefixCacheStats(),
        # compiled-step-program census, mirrored with the serving engine's
        # Stats()["compile"]["step_programs"]: this driver compiles a
        # (prefill, sample) program pair per (p_len, t_max) bucket
        step_programs=2 * len(self._decode_fns),
        # SLO scheduling counters, same mirroring contract: the batch-
        # synchronous driver admits everything up front and never
        # preempts, so no host tier exists
        preemptions=0,
        spilled_pages=0,
        restored_pages=0,
        host_bytes=0,
    ))
    self._decodes.Inc()
    # the dict every result record carries is rebuilt FROM the registry —
    # the registry is the source of truth, the dict is the view
    telemetry = observe_schema.TelemetryFromRegistry(self.metrics)
    self._last_telemetry = telemetry
    results = []
    with open(self._output_path, "a") as f:
      for i in range(b):
        rec = {
            "checkpoint_step": int(restored),
            "prompt_ids": [int(x) for x in
                           prompts[i, :int(prompt_lens[i])]],
            "output_ids": [int(x) for x in np.asarray(out[i])],
            "telemetry": telemetry,
        }
        f.write(json.dumps(rec) + "\n")
        results.append(rec)
    return results

  def Run(self, prompts: np.ndarray, prompt_lens: np.ndarray):
    """Polls for new checkpoints forever (until timeout/FINISHED marker)."""
    last_new = time.time()
    max_steps = self._task.p.train.max_steps
    try:
      while True:
        latest = self._checkpointer.LatestStep()
        if latest is not None and latest > self._last_step:
          self.DecodeOnce(latest, prompts, prompt_lens)
          last_new = time.time()
          print(f"[gshard_decode] decoded @ step {latest}", flush=True)
          if latest >= max_steps or os.path.exists(
              os.path.join(self._train_dir, "FINISHED")):
            return
        elif os.path.exists(os.path.join(self._train_dir, "FINISHED")):
          return
        elif time.time() - last_new > self._timeout:
          return
        else:
          time.sleep(self._poll_interval)
    finally:
      self._checkpointer.Close()
