"""GShard decode driver: checkpoint-watching streaming LM decode service.

Re-designs `lingvo/gshard_decode.py` (`GShardDecode:100`): a standalone job
that watches a trainer's checkpoint directory and, for every new checkpoint,
runs prompt continuations through the LM and streams results to JSONL. The
reference's infinite-infeed/outfeed-thread machinery collapses into a jitted
sampler (`lax.scan` over ExtendStep with a KV cache) plus the shared
checkpoint-polling loop.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import beam_search as beam_search_lib
from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core.nested_map import NestedMap


class GShardDecode:
  """Streams LM samples for a fixed prompt set on every new checkpoint."""

  def __init__(self, task, train_dir: str, output_path: str,
               max_decode_steps: int = 32, temperature: float = 0.0,
               poll_interval_secs: float = 10.0,
               timeout_secs: float = 3600.0,
               init_seed: int = 1234):
    """task: a TransformerLm-style task exposing InitDecodeState/ExtendStep."""
    self._task = task
    self._train_dir = train_dir
    self._output_path = output_path
    self._max_steps = max_decode_steps
    self._temperature = temperature
    self._checkpointer = checkpointer_lib.Checkpointer(train_dir)
    self._poll_interval = poll_interval_secs
    self._timeout = timeout_secs
    self._last_step = -1
    self._template = jax.eval_shape(
        self._task.CreateTrainState, jax.random.PRNGKey(init_seed))
    self._decode_fn = None

  def _GetDecodeFn(self):
    if self._decode_fn is not None:
      return self._decode_fn
    task = self._task
    t_max = self._max_steps
    temp = self._temperature

    def _Decode(theta, prompts, prompt_lens, key):
      """prompts [B, P] RIGHT-ALIGNED (left-padded) -> continuations
      [B, t_max].

      Variable-length support: each row's prompt occupies cache slots
      [P - len_i, P), so every row's last prompt token sits at slot P-1 and
      sampling starts at slot P for all rows. Left-pad slots are excluded
      from attention forever via cache_paddings (their K/V are garbage).
      Rotary attention depends only on relative positions, so global slot
      indices give the same numerics as an unpadded per-length batch.
      """
      b, p_len = prompts.shape
      total = p_len + t_max
      states = task.InitDecodeState(theta, b, total)
      # slot s is pad for row i iff s < P - len_i
      slot = jnp.arange(total)[None, :]
      cache_paddings = (slot < (p_len - prompt_lens)[:, None]).astype(
          jnp.float32)                                     # [B, total]

      # teacher-force the (right-aligned) prompt through the KV cache
      def _Prime(carry, ids_t):
        states = carry
        logits, states = task.ExtendStep(theta, ids_t[:, None], states,
                                         cache_paddings=cache_paddings)
        return states, logits

      states, logits = jax.lax.scan(_Prime, states,
                                    prompts.swapaxes(0, 1))
      last_logits = logits[-1]                             # [B, V]

      def _Sample(carry, key_t):
        states, logits = carry
        if temp > 0:
          nxt = jax.random.categorical(key_t, logits / temp, axis=-1)
        else:
          nxt = jnp.argmax(logits, axis=-1)
        nxt = nxt.astype(jnp.int32)
        new_logits, states = task.ExtendStep(theta, nxt[:, None], states,
                                             cache_paddings=cache_paddings)
        return (states, new_logits), nxt

      keys = jax.random.split(key, t_max)
      _, out_ids = jax.lax.scan(_Sample, (states, last_logits), keys)
      return out_ids.swapaxes(0, 1)                        # [B, t_max]

    self._decode_fn = jax.jit(_Decode)
    return self._decode_fn

  @staticmethod
  def _RightAlign(prompts: np.ndarray, prompt_lens: np.ndarray) -> np.ndarray:
    """Shifts each row's first len_i tokens to the row's END (left-pad)."""
    prompts = np.asarray(prompts)
    out = np.zeros_like(prompts)
    p = prompts.shape[1]
    lens = np.asarray(prompt_lens)
    if lens.shape[0] != prompts.shape[0] or (lens < 0).any() or (
        lens > p).any():
      rng = f"[{lens.min()}, {lens.max()}]" if lens.size else "[]"
      raise ValueError(
          f"prompt_lens must be [batch={prompts.shape[0]}] with values in "
          f"[0, {p}]; got shape {lens.shape}, values in {rng}")
    for i, ln in enumerate(lens):
      ln = int(ln)
      out[i, p - ln:] = prompts[i, :ln]
    return out

  def DecodeOnce(self, step: int, prompts: np.ndarray,
                 prompt_lens: np.ndarray) -> list:
    state, restored = self._checkpointer.Restore(self._template, step=step)
    fn = self._GetDecodeFn()
    aligned = self._RightAlign(prompts, prompt_lens)
    out = fn(state.theta, jnp.asarray(aligned), jnp.asarray(prompt_lens),
             jax.random.PRNGKey(restored))
    self._last_step = restored
    results = []
    with open(self._output_path, "a") as f:
      for i in range(prompts.shape[0]):
        rec = {
            "checkpoint_step": int(restored),
            "prompt_ids": [int(x) for x in
                           prompts[i, :int(prompt_lens[i])]],
            "output_ids": [int(x) for x in np.asarray(out[i])],
        }
        f.write(json.dumps(rec) + "\n")
        results.append(rec)
    return results

  def Run(self, prompts: np.ndarray, prompt_lens: np.ndarray):
    """Polls for new checkpoints forever (until timeout/FINISHED marker)."""
    last_new = time.time()
    max_steps = self._task.p.train.max_steps
    try:
      while True:
        latest = self._checkpointer.LatestStep()
        if latest is not None and latest > self._last_step:
          self.DecodeOnce(latest, prompts, prompt_lens)
          last_new = time.time()
          print(f"[gshard_decode] decoded @ step {latest}", flush=True)
          if latest >= max_steps or os.path.exists(
              os.path.join(self._train_dir, "FINISHED")):
            return
        elif os.path.exists(os.path.join(self._train_dir, "FINISHED")):
          return
        elif time.time() - last_new > self._timeout:
          return
        else:
          time.sleep(self._poll_interval)
    finally:
      self._checkpointer.Close()
