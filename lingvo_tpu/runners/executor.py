"""ExecutorTpu: the training driver loop.

Re-designs `lingvo/executor.py` (`ExecutorTpu:161`): owns the train state,
checkpointer, and program schedule; the main loop interleaves
checkpoint-save/restore with program-schedule runs and exports metrics. TPU
system init / device assignment collapses to jax device discovery; program
compilation is jit's AOT lower+compile.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import jax

from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


class ExecutorTpu:

  def __init__(self, model_params, logdir: str, schedule=None, task=None,
               init_seed: int = 1234, precompile: bool = False):
    """model_params: SingleTaskModel-style params (task + input attached).

    If `task` is given (e.g. the instance shared with the program schedule),
    it is used directly instead of instantiating a duplicate model.
    """
    self._logdir = logdir
    os.makedirs(logdir, exist_ok=True)
    if task is not None:
      self._task = task
    else:
      self._model = model_params.Instantiate()
      self._task = self._model.GetTask()
    self._task.FinalizePaths()
    # Serialize the full experiment config for reproducibility
    # (ref executor.py:233-237 trainer_params.txt).
    with open(os.path.join(logdir, "trainer_params.txt"), "w") as f:
      f.write(model_params.ToText())
    self._WriteModelAnalysis()

    tp = self._task.p.train
    self._checkpointer = checkpointer_lib.Checkpointer(
        os.path.join(logdir, "train"),
        save_interval_steps=tp.save_interval_steps,
        max_to_keep=tp.save_max_to_keep)
    self._schedule = schedule
    self._init_seed = init_seed
    self._precompile = precompile
    self._max_steps = tp.max_steps
    # early stop on eval plateau (ref base_runner._ShouldStop + EarlyStop)
    self._early_stop = None
    if getattr(tp, "early_stop_window", 0) > 0:
      from lingvo_tpu.core import early_stop as early_stop_lib
      self._metric_history = early_stop_lib.MetricHistory(
          logdir, "eval", tp.early_stop_metric)
      self._early_stop = early_stop_lib.EarlyStop(
          early_stop_lib.EarlyStop.Params().Set(
              window=tp.early_stop_window,
              tolerance=tp.early_stop_tolerance,
              metric_history=self._metric_history))

  @property
  def task(self):
    return self._task

  @property
  def checkpointer(self):
    return self._checkpointer

  def _WriteModelAnalysis(self):
    """Param-count report (ref summary_utils.ModelAnalysis:432)."""
    lines = []
    total = 0
    for path, wp in self._task.VariableSpecs().FlattenItems():
      import numpy as np
      n = int(np.prod(wp.shape)) if wp.shape else 1
      total += n
      lines.append(f"{path:<60} {str(tuple(wp.shape)):<20} {n}")
    lines.append(f"{'TOTAL':<60} {'':<20} {total}")
    with open(os.path.join(self._logdir, "model_analysis.txt"), "w") as f:
      f.write("\n".join(lines) + "\n")

  def Start(self) -> NestedMap:
    """Runs the main loop until max_steps; returns the final state."""
    state = self._task.CreateTrainState(jax.random.PRNGKey(self._init_seed))
    state, start_step = self._checkpointer.Restore(state)
    if self._precompile and self._schedule is not None:
      for prog in self._schedule.programs:
        prog.Compile(state)

    step = start_step
    while step < self._max_steps:
      if self._checkpointer.ShouldSave(step):
        self._checkpointer.Save(step, state)
      state, results = self._schedule.Run(state)
      step = int(jax.device_get(state.step))
      self._ExportMetrics(step, results)
      if self._early_stop is not None:
        tp = self._task.p.train
        # one designated eval program feeds the plateau detector — mixing
        # datasets would compare non-comparable losses
        r = results.get(tp.early_stop_program)
        if r is not None and tp.early_stop_metric in r:
          self._metric_history.ConditionalAppend(step,
                                                 r[tp.early_stop_metric])
        if self._early_stop.Stop(step):
          print(f"[executor] early stop at step {step} "
                f"(no {tp.early_stop_metric} improvement in "
                f"{tp.early_stop_window} steps)", flush=True)
          break
    self._checkpointer.Save(step, state, force=True)
    self._checkpointer.Close()
    return state

  def _ExportMetrics(self, step: int, results: dict[str, Any]):
    path = os.path.join(self._logdir, "metrics.jsonl")
    with open(path, "a") as f:
      f.write(json.dumps({"step": step, **results}, default=float) + "\n")
    summary = {k: v.get("loss", v.get("steps_per_second"))
               for k, v in results.items() if isinstance(v, dict)}
    print(f"[executor] step={step} " +
          " ".join(f"{k}={v:.4g}" for k, v in summary.items()
                   if v is not None), flush=True)
