"""ExecutorTpu: the training driver loop.

Re-designs `lingvo/executor.py` (`ExecutorTpu:161`): owns the train state,
checkpointer, and program schedule; the main loop interleaves
checkpoint-save/restore with program-schedule runs and exports metrics. TPU
system init / device assignment collapses to jax device discovery; program
compilation is jit's AOT lower+compile.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

import jax

from lingvo_tpu import observe
from lingvo_tpu.observe import goodput as goodput_lib
from lingvo_tpu.core import checkpointer as checkpointer_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap


class ExecutorTpu:

  def __init__(self, model_params, logdir: str, schedule=None, task=None,
               init_seed: int = 1234, precompile: bool = False,
               max_train_retries: int = 3, mlperf_benchmark: str = "",
               trial=None, serve_port=None, watchdog=None):
    """model_params: SingleTaskModel-style params (task + input attached).

    If `task` is given (e.g. the instance shared with the program schedule),
    it is used directly instead of instantiating a duplicate model. For a
    multi-task schedule (one exposing CreateTrainState/tasks) `task` may be
    None. `max_train_retries`: consecutive transient failures tolerated
    before giving up (each retry restores the last checkpoint — ref
    `base_runner._RunLoop:399-528` taxonomy).

    serve_port: when not None, a StatusServer over the process-global
    registry serves /metrics, /statusz, /traces, /healthz for this
    trainer (0 = ephemeral port; read `self.status_server.port`). It is
    stopped when the main loop exits. watchdog: None auto-creates a
    StallWatchdog when serve_port is set; True forces one; False
    disables; or pass a configured StallWatchdog. The watchdog beats
    once per COMPLETED program loop (telemetry-side, not dispatch-side),
    so /healthz flips when the device stalls even while a pipelined host
    keeps dispatching.
    """
    self._logdir = logdir
    os.makedirs(logdir, exist_ok=True)
    self._max_train_retries = max_train_retries
    if task is not None:
      # task built by the caller: the caller must apply
      # trial.OverrideModelParams before constructing it
      self._task = task
    elif schedule is not None and hasattr(schedule, "tasks"):
      self._task = None  # multi-task: schedule owns the task set
    else:
      if trial is not None:
        model_params = trial.OverrideModelParams(model_params)
      self._model = model_params.Instantiate()
      self._task = self._model.GetTask()
    if self._task is not None:
      self._task.FinalizePaths()
    else:
      for t in schedule.tasks.values():
        t.FinalizePaths()
    # Serialize the full experiment config for reproducibility
    # (ref executor.py:233-237 trainer_params.txt). One writer per logdir
    # under multi-host.
    if model_params is not None and jax.process_index() == 0:
      with open(os.path.join(logdir, "trainer_params.txt"), "w") as f:
        f.write(model_params.ToText())
    self._schedule = schedule
    if jax.process_index() == 0:
      self._WriteModelAnalysis()

    ref_task = (self._task if self._task is not None
                else next(iter(schedule.tasks.values())))
    tp = ref_task.p.train
    self._checkpointer = checkpointer_lib.Checkpointer(
        os.path.join(logdir, "train"),
        save_interval_steps=tp.save_interval_steps,
        max_to_keep=tp.save_max_to_keep)
    self._init_seed = init_seed
    self._pruning_schedule = None
    self._pruning_masks = None
    # MLPerf-compliance logging (ref ml_perf_log.py:80 + executor hooks)
    # hyperparameter-tuning service hook (ref base_trial.Trial + the
    # executor's trial consultation; NoOpTrial when absent)
    if trial is None:
      from lingvo_tpu.core import base_trial
      trial = base_trial.NoOpTrial()
    self._trial = trial
    self._trial_done = False
    self._mlperf = None
    from lingvo_tpu.core import ml_perf_log
    self._mllog = ml_perf_log
    if mlperf_benchmark and jax.process_index() == 0:  # single log writer
      self._mlperf = ml_perf_log.MlPerfLogger(
          os.path.join(logdir, "mlperf_log.txt"),
          benchmark=mlperf_benchmark)
      self._mlperf.Print(ml_perf_log.INIT_START)
    self._last_prune_step = -1
    self._precompile = precompile
    self._max_steps = tp.max_steps
    # early stop on eval plateau (ref base_runner._ShouldStop + EarlyStop)
    self._early_stop = None
    if getattr(tp, "early_stop_window", 0) > 0:
      from lingvo_tpu.core import early_stop as early_stop_lib
      self._metric_history = early_stop_lib.MetricHistory(
          logdir, "eval", tp.early_stop_metric)
      self._early_stop = early_stop_lib.EarlyStop(
          early_stop_lib.EarlyStop.Params().Set(
              window=tp.early_stop_window,
              tolerance=tp.early_stop_tolerance,
              metric_history=self._metric_history))
    # fleet-facing telemetry (observe/): checkpoint/recovery wall time
    # feeds the process-global goodput tracker; serve_port opens the
    # status endpoints; the watchdog beats once per completed loop
    self._goodput = goodput_lib.Get()
    self.watchdog = None
    if isinstance(watchdog, observe.StallWatchdog):
      self.watchdog = watchdog
    elif watchdog or (watchdog is None and serve_port is not None):
      self.watchdog = observe.StallWatchdog(observe.Default())
    if self.watchdog is not None:
      # liveness follows loop COMPLETION (the telemetry worker fires the
      # callback), not schedule-Run dispatch: a pipelined host dispatches
      # freely while the device hangs, so dispatch-side beats would keep
      # /healthz green through a real stall
      for prog in self._SchedulePrograms():
        set_cb = getattr(prog, "SetLoopDoneCallback", None)
        if callable(set_cb):
          set_cb(self.watchdog.Beat)
    self.status_server = None
    if serve_port is not None:
      self.status_server = observe.StatusServer(
          serve_port, registry=observe.Default(), name="executor",
          statusz_fn=self._StatuszStats,
          watchdog=self.watchdog).Start()

  def _StatuszStats(self) -> dict:
    """Structured /statusz `stats`: loop facts + every program's AOT
    compile records (wall time, XLA memory plan, flops)."""
    out = {"max_steps": self._max_steps, "compile": {}}
    for prog in self._SchedulePrograms():
      name = (getattr(getattr(prog, "p", None), "name", "")
              or type(prog).__name__)
      recs = getattr(prog, "compile_records", None)
      if recs:
        out["compile"][name] = dict(recs)
    return out

  @property
  def task(self):
    return self._task

  @property
  def checkpointer(self):
    return self._checkpointer

  def _WriteModelAnalysis(self):
    """Param-count report (ref summary_utils.ModelAnalysis:432)."""
    import numpy as np
    tasks = ({"": self._task} if self._task is not None
             else self._schedule.tasks)
    lines = []
    total = 0
    for tname, task in sorted(tasks.items()):
      prefix = f"{tname}." if tname else ""
      for path, wp in task.VariableSpecs().FlattenItems():
        n = int(np.prod(wp.shape)) if wp.shape else 1
        total += n
        lines.append(f"{prefix}{path:<60} {str(tuple(wp.shape)):<20} {n}")
    lines.append(f"{'TOTAL':<60} {'':<20} {total}")
    with open(os.path.join(self._logdir, "model_analysis.txt"), "w") as f:
      f.write("\n".join(lines) + "\n")

  def _MaybePrune(self, state: NestedMap, step: int) -> NestedMap:
    """Magnitude pruning between program runs (ref _GetMaskUpdateOp):
    masks recomputed at the schedule cadence, re-applied every loop so
    pruned weights cannot regrow."""
    tp = self._task.p.train if self._task is not None else None
    if tp is None or getattr(tp, "pruning", None) is None:
      return state
    from lingvo_tpu.core import pruning as pruning_lib
    if self._pruning_schedule is None:
      self._pruning_schedule = tp.pruning.Instantiate()
    sched = self._pruning_schedule
    if self._pruning_masks is None or sched.ShouldUpdate(
        step, self._last_prune_step):
      self._pruning_masks = pruning_lib.ComputeMasks(state.theta, sched,
                                                     step)
      self._last_prune_step = step
    state.theta = pruning_lib.ApplyMasks(state.theta, self._pruning_masks)
    if "ema_theta" in state:
      # eval/decode/export read EMA weights — they must be pruned too
      state.ema_theta = pruning_lib.ApplyMasks(state.ema_theta,
                                               self._pruning_masks)
    return state

  def _CreateTrainState(self) -> NestedMap:
    key = jax.random.PRNGKey(self._init_seed)
    if self._task is None or hasattr(self._schedule, "CreateTrainState"):
      return self._schedule.CreateTrainState(key)
    return self._task.CreateTrainState(key)

  def _PlaceState(self, state: NestedMap) -> NestedMap:
    """Places the (host-local, every-process-identical) initial state onto
    the schedule's mesh shardings (any program that declares them).
    Required under multi-host: the collective orbax save and the spanning
    jit both need global arrays, not SingleDeviceSharding host copies.
    """
    if self._schedule is None:
      return state
    from lingvo_tpu.runners import program as program_lib
    return program_lib.PlaceStateForPrograms(self._schedule.programs, state)

  def Start(self) -> NestedMap:
    """Runs the main loop until max_steps; returns the final state.

    Failure taxonomy (ref `base_runner._RunLoop:399-528`): a transient
    infrastructure error (Unavailable/Aborted/deadline — a preempted chip or
    dropped tunnel) restores the last checkpoint and continues, up to
    `max_train_retries` consecutive failures; anything else (compile errors,
    OOM, shape bugs) is fatal immediately.
    """
    state = self._PlaceState(self._CreateTrainState())
    # 'no checkpoint at all' (fresh run) is distinct from 'restored the
    # step-0 checkpoint' — warm start must apply only to the former
    fresh_run = self._checkpointer.LatestStep() is None
    with self._goodput.Track("checkpoint_restore"):
      state, start_step = self._checkpointer.Restore(state)
    if fresh_run and self._task is not None:
      rules = getattr(self._task.p.train, "init_from_checkpoint_rules", None)
      if rules:
        # fresh run: warm-start matching vars from other checkpoints
        # (ref checkpointer.py:214); resumed runs skip this.
        state = checkpointer_lib.ApplyInitFromCheckpointRules(state, rules)
      npz = getattr(self._task.p.train, "init_from_npz", "")
      if npz:
        state = checkpointer_lib.ImportNpzCheckpoint(
            state, npz,
            getattr(self._task.p.train, "init_from_npz_rules", None))
    if self._precompile and self._schedule is not None:
      for prog in self._schedule.programs:
        prog.Compile(state)

    if self._mlperf is not None:
      self._mlperf.Print(self._mllog.INIT_STOP)
      self._mlperf.Print(self._mllog.RUN_START)
    try:
      return self._MainLoop(state, start_step)
    except BaseException:
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.RUN_STOP,
                           metadata={"status": "aborted"})
        self._mlperf.Close()
      raise

  def _SchedulePrograms(self):
    return list(getattr(self._schedule, "programs", None) or [])

  def _FlushPrograms(self) -> dict:
    """Lands every program's deferred telemetry (summaries, metric fetch)
    — called before the final checkpoint so nothing is lost at exit. A
    telemetry error propagates: it is a real failed summary write/fetch.
    Returns {program name: result} for results no Run handed out yet (the
    lag-1 tail), so the caller can NaN-check and export them."""
    out = {}
    for prog in self._SchedulePrograms():
      flush = getattr(prog, "Flush", None)
      if callable(flush):
        r = flush()
        if isinstance(r, dict):
          out[getattr(getattr(prog, "p", None), "name", "") or "train"] = r
    return out

  def _RecoverPrograms(self):
    """Transient-retry hook: drain pending telemetry (the failure is
    already being handled) and restart errored infeed producers."""
    for prog in self._SchedulePrograms():
      rec = getattr(prog, "RecoverFromFailure", None)
      if callable(rec):
        try:
          rec()
        except BaseException:  # noqa: BLE001
          pass

  def _ShutdownPrograms(self):
    """Stops infeed producer threads + telemetry workers (programs stay
    restartable). Best-effort: teardown must not mask the real error."""
    for prog in self._SchedulePrograms():
      sd = getattr(prog, "Shutdown", None)
      if callable(sd):
        try:
          sd()
        except BaseException:  # noqa: BLE001
          pass

  def _MainLoop(self, state, start_step):
    try:
      return self._MainLoopBody(state, start_step)
    finally:
      try:
        # a fatal exit must not abandon an in-flight background write
        # (non-daemon worker); its own error is secondary here
        self._checkpointer.WaitForPendingSave()
      except BaseException:  # noqa: BLE001
        pass
      self._ShutdownPrograms()
      if self.status_server is not None:
        self.status_server.Stop()
        self.status_server = None
      if self.watchdog is not None:
        self.watchdog.Close()   # drop any still-armed flight recorder

  def _PipelineDepth(self) -> int:
    """The train schedule's dispatch-window depth, or 0 when the schedule
    can't be pipelined: no deterministic StepsPerCycle (multi-task
    sampling), no train program, or the program runs synchronously /
    with pipeline_depth=0 (the kill switch)."""
    sched = self._schedule
    spc = getattr(sched, "StepsPerCycle", None)
    if not callable(spc) or spc() <= 0:
      return 0
    tp = getattr(sched, "train_program", None)
    if tp is None:
      return 0
    p = tp.p
    if not (p.async_infeed and getattr(p, "defer_telemetry", False)):
      return 0
    return max(int(getattr(p, "pipeline_depth", 0) or 0), 0)

  def _SyncHostSteps(self, step: int) -> None:
    """Seeds every program's host-side step tracking at a device fence
    (start, restore, recovery)."""
    for prog in self._SchedulePrograms():
      fn = getattr(prog, "SyncHostStep", None)
      if callable(fn):
        fn(step)

  def _MainLoopBody(self, state, start_step):
    if self._PipelineDepth() >= 1:
      return self._PipelinedMainLoopBody(state, start_step)
    return self._LegacyMainLoopBody(state, start_step)

  def _LegacyMainLoopBody(self, state, start_step):
    """The pre-pipelining main loop (PR 5 shape), kept as the exact path
    for pipeline_depth=0 / sync programs / multi-task schedules: one
    blocking `device_get(state.step)` per cycle, lag-<=1 results."""
    from lingvo_tpu.core import retry as retry_lib
    step = start_step
    consecutive_failures = 0
    while step < self._max_steps:
      # Save applies the cadence policy itself; checking ShouldSave here
      # too would run its multi-host broadcast twice per cycle. (Goodput
      # attribution lives inside Save, gated on an actual write.)
      self._checkpointer.Save(step, state)
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.BLOCK_START,
                           metadata={"step": step})
      try:
        state, results = self._schedule.Run(state)
        consecutive_failures = 0
      except BaseException as e:  # noqa: BLE001
        if self._mlperf is not None:
          # keep intervals balanced: close the block before retrying/raising
          self._mlperf.Print(self._mllog.BLOCK_STOP,
                             metadata={"step": step, "status": "error"})
        if (not retry_lib.IsTransient(e) or
            consecutive_failures >= self._max_train_retries):
          raise
        consecutive_failures += 1
        delay = min(2.0 ** consecutive_failures, 30.0)
        print(f"[executor] transient failure ({type(e).__name__}: {e}); "
              f"restoring last checkpoint and retrying "
              f"({consecutive_failures}/{self._max_train_retries}) "
              f"in {delay:.0f}s", flush=True)
        with self._goodput.Track("recovery"):
          time.sleep(delay)
          # rebuild device state from the last checkpoint (ref: cleanup +
          # rebuild session + resume from checkpoint); restart any errored
          # infeed producers so the retried Run pulls fresh batches
          self._RecoverPrograms()
        with self._goodput.Track("checkpoint_restore"):
          state, step = self._checkpointer.Restore(
              self._PlaceState(self._CreateTrainState()))
        continue
      step = int(jax.device_get(state.step))
      state = self._MaybePrune(state, step)
      self._ExportMetrics(step, results)
      # trial reporting: eval AND decode program metrics; NaN train loss ->
      # report infeasible and stop (ref _RunLoop NaN-under-Vizier handling).
      # Multi-task schedules key results 'train_<task>', so scan them all.
      import math as _math
      nan_loss = any(
          isinstance(r, dict) and "loss" in r
          and not _math.isfinite(r["loss"])
          for name, r in results.items() if name.startswith("train"))
      if nan_loss:
        self._trial.ReportDone(infeasible=True, reason="nan_loss")
        self._trial_done = True
        if self._mlperf is not None:
          self._mlperf.Print(self._mllog.RUN_STOP,
                             metadata={"status": "aborted",
                                       "reason": "nan_loss"})
          self._mlperf.Close()
          self._mlperf = None
        print("[executor] NaN/Inf train loss: reporting trial infeasible "
              "and stopping", flush=True)
        break
      stop_requested = False
      for name, r in results.items():
        if isinstance(r, dict) and name.startswith(("eval", "decode")):
          stop_requested |= bool(
              self._trial.ReportEvalMeasure(step, r))
      if stop_requested or self._trial.ShouldStop():
        print(f"[executor] trial requested early stop at step {step}",
              flush=True)
        break
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.BLOCK_STOP,
                           metadata={"step": step})
        for name, r in results.items():
          if not (isinstance(r, dict) and name.startswith("eval")):
            continue
          if "accuracy" in r:  # eval_accuracy is higher-is-better ONLY
            self._mlperf.Print(self._mllog.EVAL_ACCURACY, r["accuracy"],
                               metadata={"step": step, "program": name})
          if "loss" in r:
            self._mlperf.Print("eval_loss", r["loss"],
                               metadata={"step": step, "program": name})
      if self._early_stop is not None and self._task is not None:
        tp = self._task.p.train
        # one designated eval program feeds the plateau detector — mixing
        # datasets would compare non-comparable losses
        r = results.get(tp.early_stop_program)
        if r is not None and tp.early_stop_metric in r and (
            jax.process_index() == 0):  # single writer per history file
          self._metric_history.ConditionalAppend(step,
                                                 r[tp.early_stop_metric])
        # process 0 decides (it owns the history file; a read-write race
        # could diverge the loop and deadlock the collectives), all follow
        should_stop = (bool(self._early_stop.Stop(step))
                       if jax.process_index() == 0 else False)
        if jax.process_count() > 1:
          import numpy as _np
          from jax.experimental import multihost_utils
          should_stop = bool(multihost_utils.broadcast_one_to_all(
              _np.asarray(should_stop)))
        if should_stop:
          print(f"[executor] early stop at step {step} "
                f"(no {tp.early_stop_metric} improvement in "
                f"{tp.early_stop_window} steps)", flush=True)
          break
    # land deferred telemetry (lagging <= 1 loop) before the final save so
    # summaries/metrics are complete when FINISHED appears; the tail
    # result the lag-1 return path never surfaced still gets its metrics
    # row and NaN check here
    flushed = self._FlushPrograms()
    if flushed:
      self._ExportMetrics(step, flushed)
      import math as _math
      tail_nan = any(
          isinstance(r, dict) and "loss" in r
          and not _math.isfinite(r["loss"])
          for name, r in flushed.items() if name.startswith("train"))
      if tail_nan and not self._trial_done:
        self._trial.ReportDone(infeasible=True, reason="nan_loss")
        self._trial_done = True
        print("[executor] NaN/Inf train loss in final deferred loop: "
              "reporting trial infeasible", flush=True)
    if self._mlperf is not None:
      self._mlperf.Print(self._mllog.RUN_STOP,
                         metadata={"status": "success", "step": step})
      self._mlperf.Close()
    if not self._trial_done:
      self._trial.ReportDone()
    self._checkpointer.Save(step, state, force=True)
    self._checkpointer.Close()
    # marker for follower jobs (evaler/decoder pollers): training is over —
    # process the final checkpoint and exit instead of idling to timeout
    if jax.process_index() == 0:
      with open(os.path.join(self._checkpointer.train_dir, "FINISHED"),
                "w") as f:
        f.write(str(step))
    return state

  def _PipelinedMainLoopBody(self, state, start_step):
    """The fully pipelined main loop (pipeline_depth >= 1): infeed,
    compute, checkpointing, and cadence decisions run as independent
    pipelines.

    - Host-side step tracking: after a successful cycle the step is
      `start + cycles x StepsPerCycle()` — no `device_get(state.step)`
      on the steady-state path; the device counter is re-read only at
      the fences that already exist (restore, recovery).
    - The dispatch window lives in TrainProgram ($pipeline_depth loops'
      telemetry may be unresolved at Run exit); this loop never blocks
      on Run's stale return value.
    - Checkpoint saves snapshot on this thread and write on a background
      worker (Checkpointer.SaveAsync); restore/final-save/recovery cross
      the WaitForPendingSave barrier.
    - Cadence decisions (NaN-stop, early-stop, trial, mlperf markers)
      consume the completed-loop stream via PollCompletedResults, so they
      fire within <= pipeline_depth loops of the offending step; eval
      results are fresh (the schedule flushes the train window at eval
      boundaries) and the exit path flushes + re-runs the decisions on
      the tail (docs/pipelined_executor.md).
    """
    from lingvo_tpu.core import retry as retry_lib
    sched = self._schedule
    steps_per_cycle = int(sched.StepsPerCycle())
    step = start_step
    self._SyncHostSteps(step)
    consecutive_failures = 0
    while step < self._max_steps:
      # cadence save: ShouldSave runs inside (once — it may broadcast
      # multi-host); the orbax write overlaps the cycles dispatched below.
      # The save decision needs no telemetry, only the state reference,
      # which is consistent by construction (in-flight but ordered).
      self._checkpointer.SaveAsync(step, state)
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.BLOCK_START,
                           metadata={"step": step})
      try:
        state, run_results = self._schedule.Run(state)
        consecutive_failures = 0
      except BaseException as e:  # noqa: BLE001
        if self._mlperf is not None:
          self._mlperf.Print(self._mllog.BLOCK_STOP,
                             metadata={"step": step, "status": "error"})
        if (not retry_lib.IsTransient(e) or
            consecutive_failures >= self._max_train_retries):
          raise
        consecutive_failures += 1
        delay = min(2.0 ** consecutive_failures, 30.0)
        print(f"[executor] transient failure ({type(e).__name__}: {e}); "
              f"restoring last checkpoint and retrying "
              f"({consecutive_failures}/{self._max_train_retries}) "
              f"in {delay:.0f}s", flush=True)
        with self._goodput.Track("recovery"):
          time.sleep(delay)
          # drain the dispatch window (results straddling the failure are
          # unreliable) and restart errored infeed producers
          self._RecoverPrograms()
        with self._goodput.Track("checkpoint_restore"):
          # Restore crosses WaitForPendingSave: never read around an
          # in-flight background write
          state, step = self._checkpointer.Restore(
              self._PlaceState(self._CreateTrainState()))
        self._SyncHostSteps(step)  # fence: host arithmetic re-seeds here
        continue
      step += steps_per_cycle
      state = self._MaybePrune(state, step)
      # telemetry-driven cadence: decisions run over loops that COMPLETED
      # (each exactly once, <= pipeline_depth stale), plus this cycle's
      # inline eval/decode results (fresh — the schedule flushed the train
      # window before running them). Run's returned train result is the
      # same stream lagged, so it is deliberately ignored here.
      completed = []
      for name, r in (run_results or {}).items():
        if isinstance(r, dict) and not name.startswith("train"):
          completed.append((name, r))
      for prog in self._SchedulePrograms():
        poll = getattr(prog, "PollCompletedResults", None)
        if not callable(poll):
          continue
        name = getattr(getattr(prog, "p", None), "name", "") or "train"
        for r in poll():
          completed.append((name, r))
      if self._CadenceDecisions(step, completed):
        break
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.BLOCK_STOP,
                           metadata={"step": step})
    # exit: land every in-flight loop, then run the SAME cadence pass over
    # the tail so the final metrics/NaN/trial state is complete before the
    # force save (the staleness contract's "complete final flush")
    self._FlushPrograms()
    tail = []
    for prog in self._SchedulePrograms():
      poll = getattr(prog, "PollCompletedResults", None)
      if not callable(poll):
        continue
      name = getattr(getattr(prog, "p", None), "name", "") or "train"
      for r in poll():
        tail.append((name, r))
    if tail:
      self._CadenceDecisions(step, tail)
    if self._mlperf is not None:
      self._mlperf.Print(self._mllog.RUN_STOP,
                         metadata={"status": "success", "step": step})
      self._mlperf.Close()
    if not self._trial_done:
      self._trial.ReportDone()
    # synchronous force save (barriers on any pending async write first)
    self._checkpointer.Save(step, state, force=True)
    self._checkpointer.Close()
    if jax.process_index() == 0:
      with open(os.path.join(self._checkpointer.train_dir, "FINISHED"),
                "w") as f:
        f.write(str(step))
    return state

  def _CadenceDecisions(self, step: int, completed: list) -> bool:
    """One telemetry-driven cadence pass (pipelined loop): exports metric
    rows, then NaN-stop, trial reporting, mlperf eval markers, early stop.
    `completed` is [(program name, result dict)] — train rows carry their
    own `at_step` (host-tracked), eval rows belong to the current `step`.
    Returns True when the main loop must stop."""
    import math as _math
    rows: dict[int, dict] = {}
    for name, r in completed:
      at = (int(r["at_step"]) if isinstance(r, dict) and "at_step" in r
            else step)
      rows.setdefault(at, {})[name] = r
    for at in sorted(rows):
      self._ExportMetrics(at, rows[at])
    nan_loss = any(
        isinstance(r, dict) and "loss" in r
        and not _math.isfinite(r["loss"])
        for name, r in completed if name.startswith("train"))
    if nan_loss:
      if not self._trial_done:
        self._trial.ReportDone(infeasible=True, reason="nan_loss")
        self._trial_done = True
      if self._mlperf is not None:
        self._mlperf.Print(self._mllog.RUN_STOP,
                           metadata={"status": "aborted",
                                     "reason": "nan_loss"})
        self._mlperf.Close()
        self._mlperf = None
      print("[executor] NaN/Inf train loss: reporting trial infeasible "
            "and stopping", flush=True)
      return True
    stop_requested = False
    for name, r in completed:
      if isinstance(r, dict) and name.startswith(("eval", "decode")):
        stop_requested |= bool(self._trial.ReportEvalMeasure(step, r))
    if stop_requested or self._trial.ShouldStop():
      print(f"[executor] trial requested early stop at step {step}",
            flush=True)
      return True
    if self._mlperf is not None:
      for name, r in completed:
        if not (isinstance(r, dict) and name.startswith("eval")):
          continue
        if "accuracy" in r:  # eval_accuracy is higher-is-better ONLY
          self._mlperf.Print(self._mllog.EVAL_ACCURACY, r["accuracy"],
                             metadata={"step": step, "program": name})
        if "loss" in r:
          self._mlperf.Print("eval_loss", r["loss"],
                             metadata={"step": step, "program": name})
    if self._early_stop is not None and self._task is not None:
      tp = self._task.p.train
      for name, r in completed:
        if name != tp.early_stop_program:
          continue
        if (isinstance(r, dict) and tp.early_stop_metric in r
            and jax.process_index() == 0):  # single history writer
          self._metric_history.ConditionalAppend(step,
                                                 r[tp.early_stop_metric])
      should_stop = (bool(self._early_stop.Stop(step))
                     if jax.process_index() == 0 else False)
      if jax.process_count() > 1:
        import numpy as _np
        from jax.experimental import multihost_utils
        should_stop = bool(multihost_utils.broadcast_one_to_all(
            _np.asarray(should_stop)))
      if should_stop:
        print(f"[executor] early stop at step {step} "
              f"(no {tp.early_stop_metric} improvement in "
              f"{tp.early_stop_window} steps)", flush=True)
        return True
    return False

  def _ExportMetrics(self, step: int, results: dict[str, Any]):
    if jax.process_index() != 0:
      return
    path = os.path.join(self._logdir, "metrics.jsonl")
    with open(path, "a") as f:
      f.write(json.dumps({"step": step, **results}, default=float) + "\n")
    summary = {k: v.get("loss", v.get("steps_per_second"))
               for k, v in results.items() if isinstance(v, dict)}
    print(f"[executor] step={step} " +
          " ".join(f"{k}={v:.4g}" for k, v in summary.items()
                   if v is not None), flush=True)
