"""Language model task layers (ref: lingvo/tasks/lm/layers.py + gshard LMs).

TransformerLm: embedding + repeated/stacked transformer + tied softmax over
packed or plain batches. The flagship model family: DenseLm* configs
(ref `tasks/lm/params/synthetic_packed_input.py`) instantiate this with mesh
sharding annotations for tp/dp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import transformer as transformer_lib
from lingvo_tpu.core.nested_map import NestedMap


class TransformerLm(base_model.BaseTask):
  """Decoder-only transformer LM.

  Input batch fields (packed format, ref pack_ops.cc producers):
    ids: [b, t] int32        labels: [b, t] int32
    paddings: [b, t] f32     (optional) segment_ids: [b, t] int32
    (optional) segment_pos: [b, t] int32
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 32000, "Vocabulary size.")
    p.Define("model_dim", 512, "Model dim.")
    p.Define("num_layers", 6, "Depth.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("hidden_dim", 2048, "FFN inner dim.")
    p.Define("use_repeat_layer", True,
             "Scan-over-layers (True) vs distinct layers (False).")
    p.Define("remat_policy", "full",
             "Per-layer rematerialization under use_repeat_layer: 'full' | "
             "'dots' (save matmul outputs; ~4/3x fewer bwd flops than "
             "'full') | 'none'.")
    p.Define("atten_tpl", None, "Optional attention template override.")
    p.Define(
        "mixer_tpl", None,
        "Optional O(1)-state sequence-mixer template (e.g. "
        "ssm.GatedSSMLayer.Params()). When set, SSM layers replace "
        "attention according to mixer_atten_every_n; decode/serving "
        "contracts are unchanged (the mixer implements "
        "ExtendStep/Prefill/PagedStep with a fixed [B, N, H, S] state).")
    p.Define(
        "mixer_atten_every_n", 0,
        "Hybrid-stack layout with mixer_tpl: every n-th layer (layers n, "
        "2n, ... 1-indexed) keeps full attention, the rest run the mixer — "
        "e.g. 6 gives [ssm x5, attention] blocks. 0 = every layer runs "
        "the mixer (pure-SSM stack, pageless serving). Under "
        "use_repeat_layer, num_layers must divide by n (the block is the "
        "scanned repeat body).")
    p.Define("use_rotary", True, "RoPE instead of absolute positions.")
    p.Define(
        "kv_cache_dtype", None,
        "Decode KV-cache storage dtype for every attention layer in the "
        "stack (see attention.MultiHeadedAttention.kv_cache_dtype): "
        "None = fprop dtype (bit-exact legacy caches), 'bfloat16', or "
        "'int8' (quantize-on-write with per-token-per-head scales). "
        "Serving can also override per-engine via "
        "InitPagedDecodeState(..., kv_cache_dtype=...).")
    p.Define("bidirectional", False,
             "No causal mask (BERT-style encoder; pair with an MLM task).")
    p.Define("label_smoothing", 0.0, "Label smoothing.")
    p.Define("softmax_logits_soft_max", 30.0, "Logit tanh cap (gshard-style).")
    p.Define("xent_block_size", 0,
             "If >0, train/eval loss runs the fused blockwise LM-head "
             "xent (ops/fused_xent.py) this many vocab entries at a time: "
             "ComputePredictions returns the final hidden instead of "
             "logits and the [B, T, V] logits tensor is never "
             "materialized in either direction (the peak train-step "
             "activation at vocab >= 32k). 0 = exact legacy dense path. "
             "Decode (ExtendStep/Prefill) is unaffected.")
    p.Define("softmax_num_sampled", 0,
             "If >0, train with a sampled softmax over this many log-uniform "
             "negatives (untied output head; the word-level 793k-vocab "
             "1B-words recipe). Eval still uses the full softmax.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    p.Define("atten_dropout_prob", 0.0, "Attention dropout.")
    p.Define("num_experts", 0,
             "If >0, GShard MoE: alternate dense and MoE layers "
             "(num_layers must be even; scanned as dense+MoE blocks).")
    p.Define("moe_hidden_dim", 0, "Expert FFN dim (0 = hidden_dim).")
    p.Define("moe_num_groups", 1, "Gating groups.")
    p.Define("moe_capacity_factor", 2.0, "Expert capacity factor.")
    p.Define("moe_aux_loss_weight", 0.01, "Load-balance loss weight.")
    p.Define("moe_second_expert_policy", "all", "'all' or 'random'.")
    p.Define("moe_gating_policy", "top2",
             "'top2' (learned), 'sinkhorn' (balanced top-1), or 'hash' "
             "(route by token-id hash).")
    p.Define("moe_dispatch_method", "auto",
             "MoE dispatch formulation: 'auto' | 'indexed' | 'einsum' "
             "(see gshard.MoEFeedForwardLayer).")
    p.Define("moe_dispatch_via_shard_map", None,
             "None = auto (explicit shard_map all_to_all whenever an "
             "'expert' mesh axis exists); True/False forces the path "
             "(see gshard.MoEFeedForwardLayer.dispatch_via_shard_map).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "emb",
        layers_lib.SharedEmbeddingSoftmaxLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.model_dim,
            logits_soft_max=p.softmax_logits_soft_max,
            xent_block_size=p.xent_block_size,
            weight_split_dims_mapping=("model", None)))
    if not p.use_rotary:
      self.CreateChild(
          "pos_emb",
          layers_lib.PositionalEmbeddingLayer.Params().Set(
              embedding_dim=p.model_dim))

    layer_body = transformer_lib.TransformerLayer.Params().Set(
        input_dim=p.model_dim, num_heads=p.num_heads,
        hidden_dim=p.hidden_dim, mask_self_atten=not p.bidirectional)
    atten_tpl = p.atten_tpl
    if atten_tpl is not None:
      layer_body.tr_atten_tpl.atten_tpl = atten_tpl.Copy()
    layer_body.tr_atten_tpl.atten_tpl.use_rotary_position_emb = p.use_rotary
    layer_body.tr_atten_tpl.atten_tpl.kv_cache_dtype = p.kv_cache_dtype
    layer_body.tr_atten_tpl.atten_tpl.atten_dropout_prob = p.atten_dropout_prob
    layer_body.tr_atten_tpl.atten_tpl.weight_split_dims_mapping = (
        None, "model", None)
    layer_body.tr_atten_tpl.residual_dropout_prob = p.residual_dropout_prob
    layer_body.tr_fflayer_tpl.residual_dropout_prob = p.residual_dropout_prob
    layer_body.tr_fflayer_tpl.weight_split_dims_mapping = (None, "model")

    ssm_body = None
    if p.mixer_tpl is not None:
      assert p.num_experts == 0, (
          "hybrid SSM stacks don't compose with the MoE interleave yet")
      assert not p.bidirectional, (
          "GatedSSMLayer is causal; bidirectional stacks keep attention")
      mixer_tpl = p.mixer_tpl.Copy()
      mixer_tpl.weight_split_dims_mapping = (None, "model", None)
      ssm_body = layer_body.Copy().Set(mixer_tpl=mixer_tpl)
      if p.mixer_atten_every_n == 1:
        # attention at EVERY layer: the hybrid degenerates to the plain
        # attention stack and the mixer template is never instantiated
        ssm_body = None

    if p.num_experts > 0:
      from lingvo_tpu.parallel import gshard
      assert p.num_layers % 2 == 0, "MoE interleave needs even num_layers"
      moe_tpl = gshard.MoETransformerLayer.Params()
      moe_tpl.tr_atten_tpl = layer_body.tr_atten_tpl.Copy()
      moe_tpl.moe_tpl = gshard.MoEFeedForwardLayer.Params().Set(
          hidden_dim=p.moe_hidden_dim or p.hidden_dim,
          num_experts=p.num_experts,
          num_groups=p.moe_num_groups,
          capacity_factor=p.moe_capacity_factor,
          aux_loss_weight=p.moe_aux_loss_weight,
          second_expert_policy=p.moe_second_expert_policy,
          gating_policy=p.moe_gating_policy,
          dispatch_method=p.moe_dispatch_method,
          dispatch_via_shard_map=p.moe_dispatch_via_shard_map,
          residual_dropout_prob=p.residual_dropout_prob)
      block = gshard.DenseMoEBlock.Params().Set(
          input_dim=p.model_dim, num_heads=p.num_heads,
          dense_tpl=layer_body, moe_tpl=moe_tpl)
      self.CreateChild(
          "stack",
          transformer_lib.RepeatedTransformerLayer.Params().Set(
              num_layers=p.num_layers // 2, body=block,
              remat_policy=p.remat_policy))
    elif ssm_body is not None and p.mixer_atten_every_n > 1:
      # Hybrid stack: attention at layers n, 2n, ... (1-indexed), SSM
      # elsewhere — [ssm x (n-1), attention] blocks.
      n = p.mixer_atten_every_n
      assert p.num_layers % n == 0, (p.num_layers, n)
      if p.use_repeat_layer:
        # Scan one heterogeneous block of depth n: a Stacked body with
        # explicit per-layer templates (same trick as the MoE
        # DenseMoEBlock, built from stock parts).
        block = transformer_lib.StackedTransformerLayers.Params().Set(
            num_layers=n, input_dim=p.model_dim,
            layer_tpls=[ssm_body.Copy() for _ in range(n - 1)]
            + [layer_body.Copy()],
            final_ln=False)
        self.CreateChild(
            "stack",
            transformer_lib.RepeatedTransformerLayer.Params().Set(
                num_layers=p.num_layers // n, body=block,
                remat_policy=p.remat_policy))
      else:
        tpls = [
            layer_body.Copy() if (i + 1) % n == 0 else ssm_body.Copy()
            for i in range(p.num_layers)
        ]
        self.CreateChild(
            "stack",
            transformer_lib.StackedTransformerLayers.Params().Set(
                num_layers=p.num_layers, input_dim=p.model_dim,
                layer_tpls=tpls, final_ln=False))
    elif p.use_repeat_layer:
      self.CreateChild(
          "stack",
          transformer_lib.RepeatedTransformerLayer.Params().Set(
              num_layers=p.num_layers, body=ssm_body or layer_body,
              remat_policy=p.remat_policy))
    else:
      self.CreateChild(
          "stack",
          transformer_lib.StackedTransformerLayers.Params().Set(
              num_layers=p.num_layers, input_dim=p.model_dim,
              transformer_layer_params_tpl=ssm_body or layer_body,
              final_ln=False))
    if p.softmax_num_sampled > 0:
      assert p.xent_block_size == 0, (
          "sampled softmax and the fused blockwise xent are both "
          "no-[B,T,V]-logits training paths; pick one")
      assert p.label_smoothing == 0.0, (
          "label_smoothing is not supported with the sampled softmax "
          "(the sampled xent has no smoothing term)")
      self.CreateChild(
          "sampled_softmax",
          layers_lib.SampledSoftmax.Params().Set(
              input_dim=p.model_dim, num_classes=p.vocab_size,
              num_sampled=p.softmax_num_sampled))
    self.CreateChild(
        "final_ln",
        layers_lib.LayerNorm.Params().Set(input_dim=p.model_dim))

  # -- forward ---------------------------------------------------------------

  def Inference(self):
    """'score' subgraph for serving export (ref base_model.Inference:943):
    (ids, paddings) -> per-position log-probs + per-token xent-style score.
    Shapes come from the task's input params when attached (re-export after
    editing them to serve other lengths)."""
    p = self.p
    t = getattr(getattr(p, "input", None), "seq_len", None) or 64
    example = NestedMap(
        ids=jnp.zeros((1, t), jnp.int32),
        paddings=jnp.zeros((1, t), jnp.float32))

    def score_fn(theta, inputs):
      with py_utils.EvalContext():
        preds = self.ComputePredictions(theta, inputs)
      logits = self._FullLogits(theta, preds)
      log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
      return NestedMap(log_probs=log_probs)

    return {"score": (score_fn, example)}

  def _FullLogits(self, theta, predictions):
    """Dense [..., V] logits from a predictions map — the fallback for
    consumers that genuinely need the full distribution (serving export)
    when the fused-xent gate deferred them."""
    if "logits" in predictions:
      return predictions.logits
    return self.emb.Logits(theta.emb, predictions.hidden)

  def ComputePredictions(self, theta, input_batch):
    p = self.p
    ids = input_batch.ids
    x = self.emb.EmbLookup(theta.emb, ids)
    if not p.use_rotary:
      pos = input_batch.Get("segment_pos")
      if pos is not None:
        pe = self.pos_emb.FProp(NestedMap(), position=pos.astype(jnp.float32))
      else:
        pe = self.pos_emb.FProp(NestedMap(), seq_length=ids.shape[1])[None]
      x = x + pe.astype(x.dtype)
    seg_ids = input_batch.Get("segment_ids")
    x = self.stack.FProp(theta.stack, x, paddings=input_batch.paddings,
                         segment_ids=seg_ids, token_ids=ids)
    x = self.final_ln.FProp(theta.final_ln, x)
    if p.softmax_num_sampled > 0 and not py_utils.DoEval() and \
        py_utils.HasStepSeed():
      # training with a sampled softmax: defer to ComputeLoss (no [B,T,V]
      # logits are ever materialized — the point for 793k vocabs)
      return NestedMap(hidden=x)
    if p.xent_block_size > 0:
      # fused blockwise xent: ComputeLoss / ScoreSequences stream the
      # vocab; only full-distribution consumers (_FullLogits) pay for
      # dense logits
      return NestedMap(hidden=x)
    logits = self.emb.Logits(theta.emb, x) if p.softmax_num_sampled == 0 \
        else self.sampled_softmax.Logits(
            self.ChildTheta(theta, "sampled_softmax"), x)
    return NestedMap(logits=logits)

  def ComputeLoss(self, theta, predictions, input_batch):
    p = self.p
    weights = py_utils.SequenceMask(input_batch.paddings)
    tot_weight = jnp.maximum(jnp.sum(weights), 1e-8)
    if "hidden" in predictions and p.softmax_num_sampled > 0:
      per_tok = self.sampled_softmax.XentLossFromInputs(
          self.ChildTheta(theta, "sampled_softmax"), predictions.hidden,
          input_batch.labels)
      avg_xent = jnp.sum(per_tok * weights) / tot_weight
      metrics = NestedMap(
          loss=(avg_xent, tot_weight),
          log_pplx=(avg_xent, tot_weight),
          num_predictions=(tot_weight, 1.0))
      return metrics, NestedMap(xent=per_tok)
    if "hidden" in predictions:
      # fused blockwise xent over the tied table: per-token loss AND the
      # argmax metric come out of the streaming pass — [B, T, V] logits
      # are never live in either direction
      out = self.emb.FProp(theta.emb, predictions.hidden,
                           class_ids=input_batch.labels,
                           label_smoothing=p.label_smoothing)
      correct = (out.argmax == input_batch.labels)
    else:
      out = self.emb.XentLossFromLogits(
          predictions.logits, class_ids=input_batch.labels,
          label_smoothing=p.label_smoothing)
      correct = (jnp.argmax(predictions.logits, -1) == input_batch.labels)
    avg_xent = jnp.sum(out.per_example_xent * weights) / tot_weight
    metrics = NestedMap(
        loss=(avg_xent, tot_weight),
        log_pplx=(avg_xent, tot_weight),
        fraction_of_correct_next_step_preds=(
            jnp.sum(correct * weights) / tot_weight, tot_weight),
        num_predictions=(tot_weight, 1.0))
    per_example = NestedMap(xent=out.per_example_xent)
    return metrics, per_example

  def ScoreSequences(self, theta, input_batch):
    """Per-position label log-probs for given target sequences.

    input_batch: NestedMap with ids/labels/paddings (the training batch
    format). Returns NestedMap(label_log_probs [b, t] f32, weights
    [b, t]) — log P(labels[t] | ids[<=t]) at non-padded positions.

    With the fused gate on (p.xent_block_size > 0) the score comes out of
    the blockwise streaming pass; the legacy path is the f32 log-softmax
    over dense logits. Both agree to float tolerance.
    """
    with py_utils.EvalContext():
      preds = self.ComputePredictions(theta, input_batch)
    if "hidden" in preds and self.p.softmax_num_sampled == 0:
      out = self.emb.FProp(theta.emb, preds.hidden,
                           class_ids=input_batch.labels)
      log_probs = out.label_log_probs
    else:
      logits = self._FullLogits(theta, preds)
      lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
      log_probs = jnp.take_along_axis(
          lp, input_batch.labels[..., None].astype(jnp.int32), -1)[..., 0]
    return NestedMap(label_log_probs=log_probs,
                     weights=py_utils.SequenceMask(input_batch.paddings))

  # -- decode (sampling; beam search comes from core/beam_search) ------------

  def InitDecodeState(self, theta, batch_size, max_len):
    return self.stack.InitStates(theta.stack, batch_size, max_len)

  def ExtendStep(self, theta, ids_t, states, cache_paddings=None):
    """ids_t: [b, 1] -> (logits [b, vocab], new states).

    cache_paddings: optional [b, max_len] — 1.0 marks KV-cache slots that
    must never be attended (left-padding of right-aligned variable-length
    prompts in gshard_decode).
    """
    x = self.emb.EmbLookup(theta.emb, ids_t)
    x, new_states = self.stack.ExtendStep(theta.stack, x, states,
                                          cache_paddings=cache_paddings)
    x = self.final_ln.FProp(theta.final_ln, x)
    if self.p.softmax_num_sampled > 0:
      # decode must score with the head that was TRAINED (the untied
      # sampled-softmax head), not the tied embedding
      logits = self.sampled_softmax.Logits(
          self.ChildTheta(theta, "sampled_softmax"), x)
    else:
      logits = self.emb.Logits(theta.emb, x)
    return logits[:, 0, :], new_states

  def Prefill(self, theta, ids, states, cache_paddings=None, live_len=None):
    """Chunked prefill: ids [b, c] -> (logits [b, c, vocab], new states).

    live_len: optional static bound (>= time_step + c) on how many cache
    slots the attention read touches — see MultiHeadedAttention.Prefill.

    Primes cache slots [time_step, time_step + c) with ONE batched
    attention pass per layer instead of c sequential ExtendStep calls —
    the prompt phase goes from O(prompt_len) full-cache attention calls to
    O(prompt_len / chunk). Written K/V is bit-identical to the per-token
    path; logits match it to float tolerance. Mirrors ExtendStep's
    position handling (rotary positions are the global slot indices;
    like ExtendStep — and unlike training FProp — NO absolute pos_emb is
    added for use_rotary=False models, whose decode has always been
    position-blind: absolute positions are ill-defined under the
    right-aligned ragged-prompt serving layout. Serve rotary models.)
    """
    x = self.emb.EmbLookup(theta.emb, ids)
    x, new_states = self.stack.Prefill(theta.stack, x, states,
                                       cache_paddings=cache_paddings,
                                       live_len=live_len)
    x = self.final_ln.FProp(theta.final_ln, x)
    if self.p.softmax_num_sampled > 0:
      logits = self.sampled_softmax.Logits(
          self.ChildTheta(theta, "sampled_softmax"), x)
    else:
      logits = self.emb.Logits(theta.emb, x)
    return logits, new_states

  def InitPagedDecodeState(self, theta, num_pages: int, page_size: int,
                           num_slots: int = 0,
                           kv_cache_dtype: str | None = None):
    """Global KV page pool for the continuous-batching serving engine.

    Unlike InitDecodeState there is no batch/max_len shape — capacity is
    num_pages * page_size slots shared by however many sequences the
    engine's block tables map into it (serving/engine.py owns the layout;
    it passes allocator pages + 1 so the last page is the trash page).
    num_slots: the engine's slot count, required by O(1)-state mixer
    layers (one fixed [N, H, S] state per slot); attention layers ignore
    it. kv_cache_dtype overrides p.kv_cache_dtype for this pool (a static
    string — engines pass it as a jit static arg); PagedStep needs no
    matching flag, it detects the quantized pool from the scale sidecars
    in the state."""
    return self.stack.InitPagedStates(theta.stack, num_pages, page_size,
                                      num_slots=num_slots,
                                      kv_cache_dtype=kv_cache_dtype)

  def PagedStep(self, theta, ids, states, block_tables, q_pos, in_len,
                ssm_col_states: bool = False):
    """Continuous-batching step: ids [b, c] -> (logits [b, c, vocab],
    states).

    Row b's tokens land at its sequence's global slots
    [q_pos[b], q_pos[b] + in_len[b]) through block_tables [b, t_pages];
    c == 1 is a pure decode step, c > 1 a mixed prefill/decode step
    (decode rows use in_len == 1, padding queries past in_len are
    discarded by the engine). Same position policy as Prefill: rotary
    positions are the global slot indices, no absolute pos_emb (serve
    rotary models).

    ssm_col_states: speculative-verify mode — every O(1)-state mixer in
    the stack also returns its per-column state trajectory (`col_states`)
    so the serving engine can roll rejected draft suffixes back
    (serving/spec_decode.py selects the accepted column and strips the
    extra leaf before the states re-enter the engine).
    """
    x = self.emb.EmbLookup(theta.emb, ids)
    x, new_states = self.stack.PagedStep(theta.stack, x, states,
                                         block_tables, q_pos, in_len,
                                         ssm_col_states=ssm_col_states)
    x = self.final_ln.FProp(theta.final_ln, x)
    if self.p.softmax_num_sampled > 0:
      logits = self.sampled_softmax.Logits(
          self.ChildTheta(theta, "sampled_softmax"), x)
    else:
      logits = self.emb.Logits(theta.emb, x)
    return logits, new_states

  def RaggedStep(self, theta, ids, states, block_tables, rows,
                 ssm_col_states: bool = False):
    """Packed-token continuous-batching step: ids [1, T] ->
    (logits [1, T, vocab], states).

    The ONE compiled serving program: token t belongs to engine slot
    rows.row_of[t] at global kv slot rows.pos[t] (core/ragged.py
    RaggedRows) — a decode row is 1 token, a prefill chunk several with
    ascending positions, a spec-verify window row_k + 1, and padding
    tokens (rows.valid == False) emit garbage logits the engine never
    samples from. Position policy matches PagedStep: rotary positions are
    the global slot indices, no absolute pos_emb (serve rotary models).
    ssm_col_states as in PagedStep (per-column state trajectories for
    spec-verify rollback, shaped [B, wmax, ...] here).
    """
    x = self.emb.EmbLookup(theta.emb, ids)
    x, new_states = self.stack.RaggedStep(theta.stack, x, states,
                                          block_tables, rows,
                                          ssm_col_states=ssm_col_states)
    x = self.final_ln.FProp(theta.final_ln, x)
    if self.p.softmax_num_sampled > 0:
      logits = self.sampled_softmax.Logits(
          self.ChildTheta(theta, "sampled_softmax"), x)
    else:
      logits = self.emb.Logits(theta.emb, x)
    return logits, new_states

  def PagedStepPrefix(self, theta, ids, states, block_tables, q_pos, in_len,
                      num_layers: int):
    """Early-exit PagedStep: run only the first num_layers of the stack,
    then the full final_ln + logits head — the self-speculation draft
    pass (serving/spec_decode.py). The returned states carry the prefix
    layers' writes with the suffix passed through (same pytree as
    PagedStep); callers treat them as TRANSIENT — draft steps are never
    committed, the verify step re-writes every position it keeps."""
    x = self.emb.EmbLookup(theta.emb, ids)
    x, new_states = self.stack.PagedStepPrefix(theta.stack, x, states,
                                               block_tables, q_pos, in_len,
                                               num_layers)
    x = self.final_ln.FProp(theta.final_ln, x)
    if self.p.softmax_num_sampled > 0:
      logits = self.sampled_softmax.Logits(
          self.ChildTheta(theta, "sampled_softmax"), x)
    else:
      logits = self.emb.Logits(theta.emb, x)
    return logits, new_states


class BertLm(TransformerLm):
  """Masked-LM pretraining task (ref `tasks/lm/params/wiki_bert.py` +
  `tasks/lm/layers.py` MLM usage): bidirectional encoder, loss only on
  masked positions.

  Batch fields: ids (with mask tokens applied), labels (original ids),
  masked_weights [b, t] (1.0 where a prediction is scored), paddings.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.bidirectional = True
    p.use_rotary = False  # BERT uses absolute positions
    return p

  def ComputeLoss(self, theta, predictions, input_batch):
    p = self.p
    assert p.softmax_num_sampled == 0, (
        "BertLm has no sampled-softmax loss; use xent_block_size for a "
        "no-[B,T,V] MLM head")
    if "hidden" in predictions:
      # fused blockwise xent (p.xent_block_size > 0): loss + accuracy
      # without [B, T, V] logits
      out = self.emb.FProp(theta.emb, predictions.hidden,
                           class_ids=input_batch.labels,
                           label_smoothing=p.label_smoothing)
      correct = (out.argmax == input_batch.labels)
    else:
      out = self.emb.XentLossFromLogits(
          predictions.logits, class_ids=input_batch.labels,
          label_smoothing=p.label_smoothing)
      correct = (jnp.argmax(predictions.logits, -1) == input_batch.labels)
    weights = input_batch.masked_weights * py_utils.SequenceMask(
        input_batch.paddings)
    tot_weight = jnp.maximum(jnp.sum(weights), 1e-8)
    avg_xent = jnp.sum(out.per_example_xent * weights) / tot_weight
    acc = jnp.sum(correct * weights) / tot_weight
    metrics = NestedMap(
        loss=(avg_xent, tot_weight),
        mlm_log_pplx=(avg_xent, tot_weight),
        mlm_accuracy=(acc, tot_weight),
        num_predictions=(tot_weight, 1.0))
    return metrics, NestedMap(xent=out.per_example_xent)
