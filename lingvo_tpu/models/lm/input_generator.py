"""LM inputs: synthetic packed sequences (ref
`tasks/lm/input_generator.py` + synthetic_packed_input's SyntheticTrain).

Produces the packed format the GShard LM configs train on: ids/labels/
paddings/segment_ids/segment_pos, with a deterministic Markov-ish generating
process so log-pplx is learnable and comparable across runs.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class TextLmInput(base_input_generator.FileBasedSequenceInputGenerator):
  """Real-data LM input: text lines -> tokenized (optionally packed) batches.

  The file-backed counterpart of the reference's 1B-words input
  (`tasks/lm/input_generator.py` LmInput over `text:` files +
  `pack_ops.cc` packing): each record is one sentence; with packing on,
  multiple sentences share a row with segment_ids/segment_pos (the GShard
  LM format), assigned by the native best-fit `PackSequences`.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("seq_len", 512, "Tokens per row.")
    p.Define("packing", True, "Pack several sentences per row.")
    p.bucket_upper_bound = [512]
    p.bucket_batch_limit = [16]
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    if not p.bucket_upper_bound or p.bucket_upper_bound[-1] != p.seq_len:
      p.bucket_upper_bound = [p.seq_len]
      p.bucket_batch_limit = p.bucket_batch_limit[-1:] or [16]

  def ProcessRecord(self, record: bytes):
    text = record.decode("utf-8", errors="replace").strip()
    if not text:
      return None
    ids, labels, paddings = self.StringsToIds([text], self.p.seq_len)
    n = int((1.0 - paddings[0]).sum())
    if n <= 1:
      return None
    return NestedMap(
        ids=ids[0], labels=labels[0], paddings=paddings[0],
        weights=(1.0 - paddings[0]).astype(np.float32),
        bucket_key=n)

  # -- packed path -----------------------------------------------------------
  def _Batches(self):
    if not self.p.packing:
      yield from super()._Batches()
      return
    from lingvo_tpu.ops import native
    p = self.p
    rows = p.bucket_batch_limit[-1]
    t = p.seq_len
    pending: list[NestedMap] = []
    source = iter(self._MakeSource())
    while True:
      # keep a pool ~2 batches deep so best-fit packing has choices
      while len(pending) < rows * 8:
        rec = next(source, None)
        if rec is None:
          break
        ex = self.ProcessRecord(rec)
        if ex is not None:
          pending.append(ex)
      if not pending:
        return
      lens = np.asarray([ex.bucket_key for ex in pending], np.int32)
      row, off = native.PackSequences(lens, rows, t)
      ids, seg_ids, seg_pos, extras, used = native.ApplyPacking(
          [ex.ids[:int(ex.bucket_key)] for ex in pending], row, off, rows, t,
          extra_payloads={
              "labels": [ex.labels[:int(ex.bucket_key)] for ex in pending]},
          return_used=True)
      labels = extras["labels"]
      if not used:
        # nothing fit (all sequences longer than t): drop the pool head
        pending = pending[rows:]
        continue
      paddings = (seg_ids == 0).astype(np.float32)
      yield NestedMap(ids=ids, labels=labels, paddings=paddings,
                      segment_ids=seg_ids, segment_pos=seg_pos,
                      weights=(1.0 - paddings).astype(np.float32))
      used_set = set(used)
      pending = [ex for i, ex in enumerate(pending) if i not in used_set]


class SyntheticLmInput(base_input_generator.BaseInputGenerator):
  """Deterministic synthetic LM batches.

  Each segment is a random pattern of `pattern_len` tokens tiled to the
  segment length: after one period the continuation is fully determined by
  context (the classic induction-head task), so log-pplx falls well below
  the uniform bound as the model learns — a usable convergence signal.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("seq_len", 512, "Tokens per row.")
    p.Define("vocab_size", 32000, "Vocab.")
    p.Define("pattern_len", 8, "Period of the repeated pattern.")
    p.Define("packing", True, "Emit segment_ids/segment_pos (2 segments).")
    p.Define("seed", 0, "Base seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _Sequence(self, rng, length):
    pat = rng.randint(1, self.p.vocab_size, self.p.pattern_len)
    reps = -(-length // self.p.pattern_len)
    return np.tile(pat, reps)[:length].astype(np.int32)

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 7919 * self._step) % (2**31))
    self._step += 1
    b, t = p.batch_size, p.seq_len
    ids = np.zeros((b, t), np.int32)
    labels = np.zeros((b, t), np.int32)
    segment_ids = np.zeros((b, t), np.int32)
    segment_pos = np.zeros((b, t), np.int32)
    paddings = np.zeros((b, t), np.float32)
    for i in range(b):
      if p.packing:
        split = t // 2
        segs = [(0, split), (split, t)]
      else:
        segs = [(0, t)]
      for si, (s, e) in enumerate(segs):
        seq = self._Sequence(rng, e - s + 1)
        ids[i, s:e] = seq[:-1]
        labels[i, s:e] = seq[1:]
        segment_ids[i, s:e] = si + 1
        segment_pos[i, s:e] = np.arange(e - s)
    out = NestedMap(ids=ids, labels=labels, paddings=paddings)
    if p.packing:
      out.segment_ids = segment_ids
      out.segment_pos = segment_pos
    return out


class SyntheticBertInput(base_input_generator.BaseInputGenerator):
  """Masked-LM batches over the same learnable pattern process as
  SyntheticLmInput: 15% of content positions replaced by mask_id (80%) /
  random token (10%) / kept (10%), BERT-style."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("seq_len", 128, "Tokens per row.")
    p.Define("vocab_size", 32000, "Vocab (mask_id must be < vocab).")
    p.Define("pattern_len", 8, "Pattern period.")
    p.Define("mask_prob", 0.15, "Fraction of positions scored.")
    p.Define("mask_id", 3, "The [MASK] token id.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 6029 * self._step) % (2**31))
    self._step += 1
    b, t = p.batch_size, p.seq_len
    labels = np.zeros((b, t), np.int32)
    for i in range(b):
      pat = rng.randint(4, p.vocab_size, p.pattern_len)
      reps = -(-t // p.pattern_len)
      labels[i] = np.tile(pat, reps)[:t]
    masked = rng.rand(b, t) < p.mask_prob
    ids = labels.copy()
    action = rng.rand(b, t)
    ids[masked & (action < 0.8)] = p.mask_id
    rand_tok = rng.randint(4, p.vocab_size, (b, t))
    repl = masked & (action >= 0.8) & (action < 0.9)
    ids[repl] = rand_tok[repl]
    return NestedMap(
        ids=ids, labels=labels,
        masked_weights=masked.astype(np.float32),
        paddings=np.zeros((b, t), np.float32))
