"""LM inputs: synthetic packed sequences (ref
`tasks/lm/input_generator.py` + synthetic_packed_input's SyntheticTrain).

Produces the packed format the GShard LM configs train on: ids/labels/
paddings/segment_ids/segment_pos, with a deterministic Markov-ish generating
process so log-pplx is learnable and comparable across runs.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class SyntheticLmInput(base_input_generator.BaseInputGenerator):
  """Deterministic synthetic LM batches.

  Each segment is a random pattern of `pattern_len` tokens tiled to the
  segment length: after one period the continuation is fully determined by
  context (the classic induction-head task), so log-pplx falls well below
  the uniform bound as the model learns — a usable convergence signal.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("seq_len", 512, "Tokens per row.")
    p.Define("vocab_size", 32000, "Vocab.")
    p.Define("pattern_len", 8, "Period of the repeated pattern.")
    p.Define("packing", True, "Emit segment_ids/segment_pos (2 segments).")
    p.Define("seed", 0, "Base seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _Sequence(self, rng, length):
    pat = rng.randint(1, self.p.vocab_size, self.p.pattern_len)
    reps = -(-length // self.p.pattern_len)
    return np.tile(pat, reps)[:length].astype(np.int32)

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 7919 * self._step) % (2**31))
    self._step += 1
    b, t = p.batch_size, p.seq_len
    ids = np.zeros((b, t), np.int32)
    labels = np.zeros((b, t), np.int32)
    segment_ids = np.zeros((b, t), np.int32)
    segment_pos = np.zeros((b, t), np.int32)
    paddings = np.zeros((b, t), np.float32)
    for i in range(b):
      if p.packing:
        split = t // 2
        segs = [(0, split), (split, t)]
      else:
        segs = [(0, t)]
      for si, (s, e) in enumerate(segs):
        seq = self._Sequence(rng, e - s + 1)
        ids[i, s:e] = seq[:-1]
        labels[i, s:e] = seq[1:]
        segment_ids[i, s:e] = si + 1
        segment_pos[i, s:e] = np.arange(e - s)
    out = NestedMap(ids=ids, labels=labels, paddings=paddings)
    if p.packing:
      out.segment_ids = segment_ids
      out.segment_pos = segment_pos
    return out
