"""One-Billion-Words LM configs (ref:
`tasks/lm/params/one_billion_wds.py:138` WordLevelOneBwdsSimpleSampledSoftmax
and the transformer variants).

Model shapes at reference parity; input is the synthetic packed generator
until the native pipeline feeds the real 1B-words shards (the C++ yielder +
vocab tokenizer in ops/ already handle that format:
`text:<shards>` + VocabTokenizer over the 793k-word vocab).
"""

from __future__ import annotations

import os

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.core import tokenizers
from lingvo_tpu.models.lm import input_generator
from lingvo_tpu.models.lm import layers as lm_layers

DATA_DIR = os.environ.get("LINGVO_TPU_DATA_DIR", "/tmp/lingvo_tpu_data")


@model_registry.RegisterSingleTaskModel
class OneBWdsTransformerLm(base_model_params.SingleTaskModelParams):
  """Word-level transformer LM on 1B-words-scale shapes."""

  VOCAB = 32000  # subword; the ref word-level 793k vocab needs the sampled
                 # softmax (roadmap)
  SEQ = 512
  BATCH = 32
  MODEL_DIM = 1024
  NUM_LAYERS = 20
  NUM_HEADS = 16
  HIDDEN_DIM = 4096

  def Train(self):
    return input_generator.SyntheticLmInput.Params().Set(
        batch_size=self.BATCH, seq_len=self.SEQ, vocab_size=self.VOCAB,
        packing=True)

  def Test(self):
    return input_generator.SyntheticLmInput.Params().Set(
        batch_size=self.BATCH, seq_len=self.SEQ, vocab_size=self.VOCAB,
        packing=True, seed=7)

  def Task(self):
    p = lm_layers.TransformerLm.Params()
    p.name = "one_billion_wds"
    p.vocab_size = self.VOCAB
    p.model_dim = self.MODEL_DIM
    p.num_layers = self.NUM_LAYERS
    p.num_heads = self.NUM_HEADS
    p.hidden_dim = self.HIDDEN_DIM
    p.residual_dropout_prob = 0.1
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.LinearRampupCosineDecay.Params().Set(
            warmup_steps=4000, total_steps=500_000),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class OneBWdsRealData(OneBWdsTransformerLm):
  """1B-words on real shards through the native pipeline: C++ record yielder
  over `text:` shards -> WPM tokenizer -> packed rows (ref
  `tasks/lm/params/one_billion_wds.py` dataset layout; set
  LINGVO_TPU_DATA_DIR to the corpus root with
  `1bwds/training-monolingual.tokenized.shuffled/news.en-*` shards and a
  `1bwds/vocab.wpm.txt` wordpiece vocab)."""

  def _Input(self, pattern: str, seed: int):
    return input_generator.TextLmInput.Params().Set(
        file_pattern=f"text:{DATA_DIR}/1bwds/{pattern}",
        tokenizer=tokenizers.WpmTokenizer.Params().Set(
            vocab_filepath=f"{DATA_DIR}/1bwds/vocab.wpm.txt",
            vocab_size=self.VOCAB),
        seq_len=self.SEQ,
        bucket_upper_bound=[self.SEQ],
        bucket_batch_limit=[self.BATCH],
        packing=True,
        seed=seed)

  def Train(self):
    return self._Input("training-monolingual.tokenized.shuffled/news.en-*",
                       seed=301)

  def Test(self):
    p = self._Input("heldout-monolingual.tokenized.shuffled/news.en.heldout-*",
                    seed=7)
    return p.Set(shuffle=False, max_epochs=1, require_sequential_order=True)


@model_registry.RegisterSingleTaskModel
class WordLevelOneBwdsSampledSoftmax(OneBWdsTransformerLm):
  """Word-level 1B-words with a sampled softmax (ref
  `one_billion_wds.py:138` WordLevelOneBwdsSimpleSampledSoftmax): the
  793k-word vocabulary trains against 4096 log-uniform negatives — full
  [B, T, 793k] logits are never materialized."""

  VOCAB = 793_470
  NUM_SAMPLED = 4096

  def Task(self):
    p = super().Task()
    p.softmax_num_sampled = self.NUM_SAMPLED
    return p
