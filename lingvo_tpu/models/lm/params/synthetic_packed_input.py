"""Dense LM experiment configs on synthetic packed input.

Ref `lingvo/tasks/lm/params/synthetic_packed_input.py:161-289`: the DenseLm*
family defines the scale points (8B on 128 cores, 128B on 8x8, 175B on 32x32,
1T). Here: same model shapes, TPU-native sharding via mesh axis names
('data', 'model') instead of DEVICE_MESH_SHAPE wid/zigzag orderings — the
mesh geometry itself comes from runtime flags / parallel.mesh.
"""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.lm import input_generator
from lingvo_tpu.models.lm import layers as lm_layers


class DenseLmTemplate(base_model_params.SingleTaskModelParams):
  """Shared recipe for the DenseLm family (ref :107 DenseLmTemplate)."""

  SEQUENCE_LENGTH = 1024
  BATCH_SIZE = 8  # per host
  VOCAB_SIZE = 32000
  MODEL_DIM = 1024
  NUM_LAYERS = 8
  NUM_HEADS = 16
  HIDDEN_DIM = 4096
  USE_REPEAT = True
  # If >0, the fused blockwise LM-head xent (docs/fused_xent.md): the
  # [B, T, V] logits tensor — the peak train-step activation, and the one
  # remat can't save — is never materialized. Prefer a value dividing
  # VOCAB_SIZE; 0 = legacy dense head.
  XENT_BLOCK_SIZE = 0
  LEARNING_RATE = 2.5e-4
  MAX_STEPS = 1_000_000

  def Train(self):
    return input_generator.SyntheticLmInput.Params().Set(
        batch_size=self.BATCH_SIZE, seq_len=self.SEQUENCE_LENGTH,
        vocab_size=self.VOCAB_SIZE, packing=True)

  def Test(self):
    return input_generator.SyntheticLmInput.Params().Set(
        batch_size=self.BATCH_SIZE, seq_len=self.SEQUENCE_LENGTH,
        vocab_size=self.VOCAB_SIZE, packing=True, seed=99)

  def Task(self):
    p = lm_layers.TransformerLm.Params()
    p.name = "lm"
    p.vocab_size = self.VOCAB_SIZE
    p.model_dim = self.MODEL_DIM
    p.num_layers = self.NUM_LAYERS
    p.num_heads = self.NUM_HEADS
    p.hidden_dim = self.HIDDEN_DIM
    p.use_repeat_layer = self.USE_REPEAT
    p.xent_block_size = self.XENT_BLOCK_SIZE
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=self.LEARNING_RATE,
        optimizer=opt_lib.Adafactor.Params().Set(
            beta1=0.9, multiply_by_parameter_scale=False),
        lr_schedule=sched_lib.LinearRampupCosineDecay.Params().Set(
            warmup_steps=1000, total_steps=self.MAX_STEPS),
        clip_gradient_norm_to_value=1.0)
    p.train.max_steps = self.MAX_STEPS
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class DenseLmTiny(DenseLmTemplate):
  """Smoke-test scale: trains on CPU in seconds."""

  SEQUENCE_LENGTH = 64
  BATCH_SIZE = 4
  VOCAB_SIZE = 128
  MODEL_DIM = 64
  NUM_LAYERS = 2
  NUM_HEADS = 4
  HIDDEN_DIM = 128
  LEARNING_RATE = 3e-3
  MAX_STEPS = 2000


@model_registry.RegisterSingleTaskModel
class DenseLm1B(DenseLmTemplate):
  """~1.3B params; single-host bench scale."""

  SEQUENCE_LENGTH = 1024
  MODEL_DIM = 2048
  NUM_LAYERS = 24
  NUM_HEADS = 16
  HIDDEN_DIM = 8192


@model_registry.RegisterSingleTaskModel
class DenseLmWord793k(DenseLmTemplate):
  """Word-level one-billion-words head (the reference's 793k-vocab
  recipe): dense [B, T, 793k] logits are prohibitive — ~6.5 GB f32 per
  step at this geometry before the backward — so the fused blockwise
  head (docs/fused_xent.md) is on. The alternative no-[B,T,V] recipe is
  `softmax_num_sampled` (sampled softmax, untied head); this config is
  the exact-loss tied-head variant."""

  SEQUENCE_LENGTH = 256
  MODEL_DIM = 1024
  NUM_LAYERS = 8
  VOCAB_SIZE = 793_600    # 793471 words rounded up to a 1024 multiple
  XENT_BLOCK_SIZE = 1024  # divides VOCAB_SIZE: no masking, no weight pad


@model_registry.RegisterSingleTaskModel
class DenseLm8B(DenseLmTemplate):
  """Ref DenseLm8B2x2 (`synthetic_packed_input.py:161-181`): 4 transformer
  blocks, model_dim 8192, ff 65536, 128 heads, seq 1024 (~8B params)."""

  SEQUENCE_LENGTH = 1024
  MODEL_DIM = 8192
  NUM_LAYERS = 4
  NUM_HEADS = 128
  HIDDEN_DIM = 65536


@model_registry.RegisterSingleTaskModel
class DenseLmSsmHybrid(DenseLmTemplate):
  """Hybrid O(1)-cache stack: attention every 6th layer, gated-SSD SSM
  mixers elsewhere (docs/sequence_mixers.md). Decode state per sequence is
  10 SSM matrices + 2 KV caches instead of 12 KV caches — ~6x less decode
  HBM at seq 1024, flat in sequence length for the SSM share."""

  SEQUENCE_LENGTH = 1024
  MODEL_DIM = 1024
  NUM_LAYERS = 12
  NUM_HEADS = 16
  HIDDEN_DIM = 4096
  MIXER_ATTEN_EVERY_N = 6
  SSM_STATE_DIM = 64
  SSM_CHUNK_SIZE = 64

  def Task(self):
    from lingvo_tpu.core import ssm
    p = super().Task()
    p.mixer_tpl = ssm.GatedSSMLayer.Params().Set(
        state_dim=self.SSM_STATE_DIM, chunk_size=self.SSM_CHUNK_SIZE)
    p.mixer_atten_every_n = self.MIXER_ATTEN_EVERY_N
    return p


@model_registry.RegisterSingleTaskModel
class DenseLmSsmHybridTiny(DenseLmSsmHybrid):
  """Smoke-test scale of the hybrid stack: attention every 2nd layer;
  decodes on CPU in seconds (serving/bench/test harnesses)."""

  SEQUENCE_LENGTH = 64
  BATCH_SIZE = 4
  VOCAB_SIZE = 128
  MODEL_DIM = 64
  NUM_LAYERS = 2
  NUM_HEADS = 4
  HIDDEN_DIM = 128
  MIXER_ATTEN_EVERY_N = 2
  SSM_STATE_DIM = 16
  SSM_CHUNK_SIZE = 8
  LEARNING_RATE = 3e-3
  MAX_STEPS = 2000


@model_registry.RegisterSingleTaskModel
class MoELmTiny(DenseLmTemplate):
  """Smoke-test MoE LM (8 experts, alternate dense/MoE layers)."""

  SEQUENCE_LENGTH = 64
  BATCH_SIZE = 4
  VOCAB_SIZE = 128
  MODEL_DIM = 64
  NUM_LAYERS = 2
  NUM_HEADS = 4
  HIDDEN_DIM = 128
  LEARNING_RATE = 3e-3
  NUM_EXPERTS = 8

  def Task(self):
    p = super().Task()
    p.num_experts = self.NUM_EXPERTS
    p.moe_num_groups = self.BATCH_SIZE
    return p


@model_registry.RegisterSingleTaskModel
class MoELm64E(DenseLmTemplate):
  """The BASELINE north-star config: 64-expert GShard MoE transformer
  (ref `tasks/lm/README.md` MoE models; target >=45% MFU on v5p-128)."""

  SEQUENCE_LENGTH = 1024
  BATCH_SIZE = 16
  MODEL_DIM = 1024
  NUM_LAYERS = 24
  NUM_HEADS = 16
  HIDDEN_DIM = 4096
  NUM_EXPERTS = 64

  def Task(self):
    p = super().Task()
    p.num_experts = self.NUM_EXPERTS
    # auto groups = data_axis * expert_axis: groups shard over both axes so
    # the explicit shard_map all-to-all dispatch engages and no data slice
    # recomputes another's experts; the GSPMD einsum fallback at
    # non-divisible group counts costs ~2x the collective-permutes (see
    # tools/collective_attribution.py, round-5 analysis)
    p.moe_num_groups = 0
    p.moe_second_expert_policy = "random"
    # save matmul + dispatched-activation outputs instead of replaying the
    # whole block (incl. both all-to-alls) in the backward pass
    p.remat_policy = "dots"
    return p


@model_registry.RegisterSingleTaskModel
class DenseLm128B(DenseLmTemplate):
  """Ref DenseLm128B8x8 (`synthetic_packed_input.py:200-237`): 64 blocks at
  the 8B dims (~137.7B params per the reference's comment)."""

  SEQUENCE_LENGTH = 1024
  MODEL_DIM = 8192
  NUM_LAYERS = 64
  NUM_HEADS = 128
  HIDDEN_DIM = 65536


@model_registry.RegisterSingleTaskModel
class DenseLm175B(DenseLmTemplate):
  """Ref DenseLm175B32x32 (`synthetic_packed_input.py:238-288`): GPT-3-scale
  shapes — 96 blocks, model_dim 12288, ff 49152, 96 heads, seq 2048 — for a
  2048-core slice (mesh data x model from runtime flags)."""

  SEQUENCE_LENGTH = 2048
  MODEL_DIM = 12288
  NUM_LAYERS = 96
  NUM_HEADS = 96
  HIDDEN_DIM = 49152
  BATCH_SIZE = 1  # per host; global batch from the data axis


@model_registry.RegisterSingleTaskModel
class DenseLm1T(DenseLmTemplate):
  """Ref DenseLm1T16x16 (`synthetic_packed_input.py:330`): ~1T params with
  512-way model parallelism — 128 blocks, model_dim 16384, ff 262144."""

  SEQUENCE_LENGTH = 512
  MODEL_DIM = 16384
  NUM_LAYERS = 128
  NUM_HEADS = 256
  HIDDEN_DIM = 262144
  BATCH_SIZE = 1
