"""BERT masked-LM configs (ref `lingvo/tasks/lm/params/wiki_bert.py`):
bidirectional TransformerLm + MLM loss on synthetic masked batches until the
native pipeline feeds real wiki shards (TextLmInput + a masking processor)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.lm import input_generator
from lingvo_tpu.models.lm import layers as lm_layers


class BertTemplate(base_model_params.SingleTaskModelParams):
  """Shared BERT recipe."""

  SEQUENCE_LENGTH = 512
  BATCH_SIZE = 16
  VOCAB_SIZE = 32000
  MODEL_DIM = 768
  NUM_LAYERS = 12
  NUM_HEADS = 12
  HIDDEN_DIM = 3072
  LEARNING_RATE = 1e-4
  MAX_STEPS = 1_000_000

  def Train(self):
    return input_generator.SyntheticBertInput.Params().Set(
        batch_size=self.BATCH_SIZE, seq_len=self.SEQUENCE_LENGTH,
        vocab_size=self.VOCAB_SIZE)

  def Test(self):
    return input_generator.SyntheticBertInput.Params().Set(
        batch_size=self.BATCH_SIZE, seq_len=self.SEQUENCE_LENGTH,
        vocab_size=self.VOCAB_SIZE, seed=99)

  def Task(self):
    p = lm_layers.BertLm.Params()
    p.name = "bert"
    p.vocab_size = self.VOCAB_SIZE
    p.model_dim = self.MODEL_DIM
    p.num_layers = self.NUM_LAYERS
    p.num_heads = self.NUM_HEADS
    p.hidden_dim = self.HIDDEN_DIM
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=self.LEARNING_RATE,
        optimizer=opt_lib.AdamW.Params().Set(beta2=0.999,
                                             weight_decay=0.01),
        lr_schedule=sched_lib.LinearRampupCosineDecay.Params().Set(
            warmup_steps=10000, total_steps=self.MAX_STEPS),
        clip_gradient_norm_to_value=1.0)
    p.train.max_steps = self.MAX_STEPS
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class BertBase(BertTemplate):
  """BERT-Base shapes (ref wiki_bert Wiki/BertBase)."""


@model_registry.RegisterSingleTaskModel
class BertLarge(BertTemplate):
  """BERT-Large shapes."""

  MODEL_DIM = 1024
  NUM_LAYERS = 24
  NUM_HEADS = 16
  HIDDEN_DIM = 4096


@model_registry.RegisterSingleTaskModel
class BertTiny(BertTemplate):
  """Smoke-test scale (short pattern period: the masked-copy rule is
  learnable in a few hundred steps instead of waiting out the induction
  phase transition)."""

  SEQUENCE_LENGTH = 64
  BATCH_SIZE = 8
  VOCAB_SIZE = 128
  MODEL_DIM = 64
  NUM_LAYERS = 2
  NUM_HEADS = 4
  HIDDEN_DIM = 128
  LEARNING_RATE = 1e-3

  def Train(self):
    return super().Train().Set(pattern_len=4)

  def Test(self):
    return super().Test().Set(pattern_len=4)

  def Task(self):
    p = super().Task()
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p
