"""Milan dual-encoder retrieval (ref `lingvo/tasks/milan/dual_encoder.py`):
two modality encoders projected into a shared space, trained with the
symmetric in-batch contrastive softmax loss, evaluated by retrieval
recall@k.

TPU-first: the in-batch similarity matrix is one [B, B] matmul (MXU);
under data parallelism the batch dim shards and XLA inserts the all-gather
of the opposite tower's embeddings.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


class MlpEncoder(base_layer.BaseLayer):
  """Feature-vector encoder tower (image features / pooled text)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_dim", 0, "Input feature dim.")
    p.Define("hidden_dims", [256], "MLP hidden dims.")
    p.Define("output_dim", 128, "Joint embedding dim.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "mlp",
        layers_lib.FeedForwardNet.Params().Set(
            input_dim=p.input_dim,
            hidden_layer_dims=list(p.hidden_dims) + [p.output_dim],
            activation=["RELU"] * len(p.hidden_dims) + ["NONE"]))

  def FProp(self, theta, features):
    return self.mlp.FProp(theta.mlp, features)


class DualEncoderTask(base_model.BaseTask):
  """Two towers + temperature-scaled contrastive loss (ref
  `dual_encoder.py` loss + `score_functions`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("image_encoder", MlpEncoder.Params(), "Tower A.")
    p.Define("text_encoder", MlpEncoder.Params(), "Tower B.")
    p.Define("image_input_features", "image",
             "Input-batch field(s) fed to the image tower — a name or tuple "
             "of names, passed positionally (ref EncoderConfig."
             "input_features / Selector, dual_encoder.py:44-52).")
    p.Define("text_input_features", "text",
             "Input-batch field(s) fed to the text tower.")
    p.Define("init_temperature", 0.07, "Softmax temperature (learned log).")
    p.Define("recall_at", (1, 5), "Ks for retrieval recall metrics.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild("image_encoder", p.image_encoder)
    self.CreateChild("text_encoder", p.text_encoder)
    self.CreateVariable(
        "log_inv_temperature",
        WeightParams((), WeightInit.Constant(
            float(np.log(1.0 / p.init_temperature))), jnp.float32))

  @staticmethod
  def _SelectFeatures(input_batch, features):
    names = (features,) if isinstance(features, str) else tuple(features)
    return [input_batch[n] for n in names]

  def _Embed(self, theta, input_batch):
    p = self.p
    img = self.image_encoder.FProp(
        self.ChildTheta(theta, "image_encoder"),
        *self._SelectFeatures(input_batch, p.image_input_features))
    txt = self.text_encoder.FProp(
        self.ChildTheta(theta, "text_encoder"),
        *self._SelectFeatures(input_batch, p.text_input_features))
    img = img / jnp.maximum(
        jnp.linalg.norm(img, axis=-1, keepdims=True), 1e-6)
    txt = txt / jnp.maximum(
        jnp.linalg.norm(txt, axis=-1, keepdims=True), 1e-6)
    return img, txt

  def _RowValidity(self, input_batch):
    """[B] 1.0 for real examples, 0.0 for padded flush rows.

    Finite-epoch file inputs pad the last batch; padded rows arrive with
    all-padding text (`_PadBatchDim` sets *_paddings leaves to 1), and must
    not act as contrastive examples or count in recall.
    """
    for names in (self.p.text_input_features, self.p.image_input_features):
      names = (names,) if isinstance(names, str) else tuple(names)
      for n in names:
        if n == "paddings" or n.endswith("_paddings"):
          pad = input_batch[n]
          return (jnp.min(pad, axis=-1) < 0.5).astype(jnp.float32)
    return None

  def ComputePredictions(self, theta, input_batch):
    th = self.CastTheta(theta)
    img, txt = self._Embed(theta, input_batch)
    scale = jnp.exp(th.log_inv_temperature)
    sims = scale * jnp.einsum("id,jd->ij", img, txt)     # [B, B]
    return NestedMap(similarities=sims, image_emb=img, text_emb=txt,
                     example_weights=self._RowValidity(input_batch))

  def _MaskedContrastive(self, sims, valid):
    """Per-direction losses + weight, excluding invalid rows/columns."""
    b = sims.shape[0]
    labels = jnp.arange(b)
    if valid is None:
      valid = jnp.ones((b,), jnp.float32)
    neg_inf = jnp.asarray(-1e9, sims.dtype)
    # invalid examples can't serve as negatives in either direction
    col_masked = jnp.where(valid[None, :] > 0.5, sims, neg_inf)
    row_masked = jnp.where(valid[:, None] > 0.5, sims, neg_inf)
    denom = jnp.maximum(jnp.sum(valid), 1.0)
    i2t = -jnp.sum(
        jax.nn.log_softmax(col_masked, axis=1)[labels, labels] * valid
    ) / denom
    t2i = -jnp.sum(
        jax.nn.log_softmax(row_masked, axis=0)[labels, labels] * valid
    ) / denom
    return i2t, t2i, valid, denom

  def ComputeLoss(self, theta, predictions, input_batch):
    sims = predictions.similarities.astype(jnp.float32)
    b = sims.shape[0]
    labels = jnp.arange(b)
    i2t, t2i, valid, denom = self._MaskedContrastive(
        sims, predictions.example_weights)
    loss = 0.5 * (i2t + t2i)
    metrics = NestedMap(
        loss=(loss, denom),
        i2t_loss=(i2t, denom),
        t2i_loss=(t2i, denom))
    ranked = jnp.where(valid[None, :] > 0.5, sims, -1e9)
    for k in self.p.recall_at:
      if k <= b:
        topk = jnp.argsort(-ranked, axis=1)[:, :k]        # i2t retrieval
        hit = jnp.any(topk == labels[:, None], axis=1)
        metrics.Set(f"recall_at_{k}", (jnp.sum(
            hit.astype(jnp.float32) * valid) / denom, denom))
    return metrics, NestedMap()

  def Decode(self, theta, input_batch):
    preds = self.ComputePredictions(theta, input_batch)
    out = NestedMap(similarities=preds.similarities)
    if preds.example_weights is not None:
      out.example_weights = preds.example_weights
    return out

  def CreateDecoderMetrics(self):
    from lingvo_tpu.core import metrics as metrics_lib
    return {f"recall_at_{k}": metrics_lib.AverageMetric()
            for k in self.p.recall_at}

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    sims = np.asarray(decode_out.similarities)
    b = sims.shape[0]
    valid = np.asarray(decode_out.example_weights) if (
        "example_weights" in decode_out and
        decode_out.example_weights is not None) else np.ones(b)
    sims = np.where(valid[None, :] > 0.5, sims, -1e9)  # no phantom targets
    order = np.argsort(-sims, axis=1)
    for k in self.p.recall_at:
      if k <= b:
        hit = (order[:, :k] == np.arange(b)[:, None]).any(axis=1)
        for h, v in zip(hit, valid):
          if v > 0.5:
            decoder_metrics[f"recall_at_{k}"].Update(float(h))
