"""Milan inputs: paired image/text batches.

Three generators, mirroring the reference's milan input stack
(`lingvo/tasks/milan/input_generator.py`, `dataset_spec.py`,
`params/generic_datasets.py`):

- `SyntheticPairedInput`: feature-vector pairs through fixed linear maps
  (kept for the MLP-tower parity config).
- `SyntheticImageTextInput`: REAL modalities — [H, W, 3] images rendered
  from discrete sprite codes, and token sequences naming those sprites.
  Retrieval requires the conv tower to recognize sprites in pixels and the
  text tower to read them from tokens.
- `MilanFileInput`: file-backed paired records (JSON: image + token ids)
  over the native C++ record yielder, the production path.
"""

from __future__ import annotations

import json

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class SyntheticPairedInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("latent_dim", 16, "Shared latent code dim.")
    p.Define("image_dim", 64, "Image feature dim.")
    p.Define("text_dim", 48, "Text feature dim.")
    p.Define("noise", 0.1, "Observation noise.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    rng = np.random.RandomState(4242)  # fixed across train/test
    self._img_map = rng.randn(p.latent_dim, p.image_dim).astype(np.float32)
    self._txt_map = rng.randn(p.latent_dim, p.text_dim).astype(np.float32)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 92821 * self._step) % (2**31))
    self._step += 1
    z = rng.randn(p.batch_size, p.latent_dim).astype(np.float32)
    img = z @ self._img_map + p.noise * rng.randn(p.batch_size, p.image_dim)
    txt = z @ self._txt_map + p.noise * rng.randn(p.batch_size, p.text_dim)
    return NestedMap(image=img.astype(np.float32),
                     text=txt.astype(np.float32))


def RenderSprites(attr_ids: np.ndarray, sprites: np.ndarray,
                  noise: float, rng) -> np.ndarray:
  """[B, K] sprite ids + [V, H, W, 3] sprite bank -> [B, H, W, 3] images."""
  img = sprites[attr_ids].sum(axis=1)  # [B, H, W, 3]
  if noise > 0:
    img = img + noise * rng.randn(*img.shape)
  return np.clip(img, -3.0, 3.0).astype(np.float32)


class SyntheticImageTextInput(base_input_generator.BaseInputGenerator):
  """Paired ([B,H,W,3] image, [B,T] token ids) batches from sprite codes.

  Each example draws `attrs_per_example` distinct sprite ids; the image is
  the sum of those sprites' fixed random patterns (+noise), the text is the
  sprite ids as tokens (1-based; 0 is pad). Cross-modal retrieval demands
  both towers actually encode their modality.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("image_size", 16, "Square image height/width.")
    p.Define("num_sprites", 16, "Sprite vocabulary size.")
    p.Define("attrs_per_example", 3, "Sprites per example.")
    p.Define("text_len", 6, "Token row length (>= attrs_per_example).")
    p.Define("noise", 0.05, "Pixel observation noise.")
    p.Define("seed", 0, "Per-dataset seed.")
    return p

  @property
  def text_vocab_size(self) -> int:
    return self.p.num_sprites + 1  # + pad token 0

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    rng = np.random.RandomState(7321)  # sprite bank fixed across datasets
    s = p.image_size
    # smooth-ish sprites: random low-res patterns upsampled 4x
    lo = rng.randn(p.num_sprites, (s + 3) // 4, (s + 3) // 4, 3)
    self._sprites = lo.repeat(4, axis=1)[:, :s].repeat(
        4, axis=2)[:, :, :s].astype(np.float32)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 92821 * self._step) % (2 ** 31))
    self._step += 1
    b, k = p.batch_size, p.attrs_per_example
    attrs = np.stack(
        [rng.choice(p.num_sprites, size=k, replace=False) for _ in range(b)])
    image = RenderSprites(attrs, self._sprites, p.noise, rng)
    ids = np.zeros((b, p.text_len), np.int32)
    ids[:, :k] = np.sort(attrs, axis=1) + 1  # canonical order; 0 = pad
    paddings = (ids == 0).astype(np.float32)
    return NestedMap(image=image, text_ids=ids, text_paddings=paddings)


class MilanFileInput(base_input_generator.FileBasedSequenceInputGenerator):
  """File-backed paired input: one JSON record per example with
  {"image": [H, W, 3] nested list (or flat list + "image_shape"),
   "text_ids": [T'] tokens} — the production path over the native yielder
  (ref milan `dataset_spec.py` tfrecord pipelines).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("image_size", 16, "Square image size records must match.")
    p.Define("text_len", 6, "Token row length (truncate/pad records).")
    return p

  def __init__(self, params):
    params = params.Copy()
    params.bucket_upper_bound = [1]
    params.bucket_batch_limit = [params.batch_size or 8]
    super().__init__(params)

  def ProcessRecord(self, record: bytes):
    p = self.p
    try:
      ex = json.loads(record.decode("utf-8"))
      if not isinstance(ex, dict):
        return None
      img = np.asarray(ex["image"], np.float32)
      if "image_shape" in ex:
        img = img.reshape(ex["image_shape"])
      if img.shape != (p.image_size, p.image_size, 3):
        return None
      toks = np.asarray(ex["text_ids"], np.int64).reshape(-1)[:p.text_len]
    except (KeyError, ValueError, TypeError, json.JSONDecodeError,
            UnicodeDecodeError):
      return None  # malformed record: drop, never kill the pipeline
    ids = np.zeros((p.text_len,), np.int32)
    ids[:len(toks)] = toks
    return NestedMap(
        image=img, text_ids=ids,
        text_paddings=(ids == 0).astype(np.float32),
        bucket_key=1)
