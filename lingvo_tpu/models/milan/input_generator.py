"""Milan input: synthetic paired (image-feature, text-feature) batches.

Pairs share a latent code rendered through two fixed random linear maps +
noise — cross-modal retrieval is learnable but not trivial (ref milan's
image/text input pipelines over tfrecords; plug TextMtInput-style file
generators for real data)."""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class SyntheticPairedInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("latent_dim", 16, "Shared latent code dim.")
    p.Define("image_dim", 64, "Image feature dim.")
    p.Define("text_dim", 48, "Text feature dim.")
    p.Define("noise", 0.1, "Observation noise.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    rng = np.random.RandomState(4242)  # fixed across train/test
    self._img_map = rng.randn(p.latent_dim, p.image_dim).astype(np.float32)
    self._txt_map = rng.randn(p.latent_dim, p.text_dim).astype(np.float32)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 92821 * self._step) % (2**31))
    self._step += 1
    z = rng.randn(p.batch_size, p.latent_dim).astype(np.float32)
    img = z @ self._img_map + p.noise * rng.randn(p.batch_size, p.image_dim)
    txt = z @ self._txt_map + p.noise * rng.randn(p.batch_size, p.text_dim)
    return NestedMap(image=img.astype(np.float32),
                     text=txt.astype(np.float32))
