"""Milan encoder towers: conv image tower + transformer text tower.

Re-designs the reference's modality encoders (ref
`lingvo/tasks/milan/dual_encoder.py:1-120` EncoderConfig consumers,
`tasks/milan/transformers.py` GetTransformerStackWithEmbeddingInput, and the
tf-hub image towers in `tasks/milan/tf_hub_layers.py`) as TPU-native layers:
the image tower is a strided NHWC conv stack (MXU-friendly, BN in-graph)
with global average pooling; the text tower embeds token ids and runs a
batch-major transformer stack with masked mean pooling.
"""

from __future__ import annotations

import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import transformer as transformer_lib
from lingvo_tpu.core.nested_map import NestedMap


class ConvImageEncoder(base_layer.BaseLayer):
  """[B, H, W, C] images -> [B, output_dim] embeddings.

  A strided conv stack (stride 2 per block, ref tf_hub image towers'
  downsampling) + global average pool + linear projection.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_channels", 3, "Image channels.")
    p.Define("filter_counts", [32, 64, 128],
             "Output channels per stride-2 conv block.")
    p.Define("filter_size", 3, "Square kernel size.")
    p.Define("output_dim", 128, "Joint embedding dim.")
    p.Define("batch_norm", True, "BN after each conv.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    cin = p.input_channels
    convs = []
    for cout in p.filter_counts:
      convs.append(layers_lib.Conv2DLayer.Params().Set(
          filter_shape=(p.filter_size, p.filter_size, cin, cout),
          filter_stride=(2, 2),
          activation="RELU",
          batch_norm=p.batch_norm,
          has_bias=not p.batch_norm))
      cin = cout
    self.CreateChildren("convs", convs)
    self.CreateChild(
        "proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=cin, output_dim=p.output_dim, activation="NONE"))

  def FProp(self, theta, images):
    """images: [B, H, W, C] floats."""
    x = self.ToFPropDtype(images)
    for i, conv in enumerate(self.convs):
      x = conv.FProp(theta.convs[i], x)
    x = jnp.mean(x, axis=(1, 2))  # global average pool -> [B, C]
    return self.proj.FProp(theta.proj, x)


class TransformerTextEncoder(base_layer.BaseLayer):
  """[B, T] token ids (+ paddings) -> [B, output_dim] embeddings.

  Embedding + positional encoding + transformer stack + masked mean pool +
  projection (ref `tasks/milan/transformers.py`
  GetTransformerStackWithEmbeddingInput: input projection, N transformer
  layers, fixed-dim output).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Token vocabulary size.")
    p.Define("model_dim", 128, "Transformer width.")
    p.Define("num_layers", 2, "Transformer depth.")
    p.Define("num_heads", 4, "Attention heads.")
    p.Define("hidden_dim", 0, "FFN dim (0 = 4x model_dim).")
    p.Define("output_dim", 128, "Joint embedding dim.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.vocab_size > 0, "vocab_size required"
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.model_dim,
            scale_sqrt_depth=True))
    self.CreateChild(
        "pos_emb",
        layers_lib.PositionalEmbeddingLayer.Params().Set(
            embedding_dim=p.model_dim))
    tl = transformer_lib.TransformerLayer.Params().Set(
        num_heads=p.num_heads, hidden_dim=p.hidden_dim or 4 * p.model_dim)
    self.CreateChild(
        "stack",
        transformer_lib.StackedTransformerLayers.Params().Set(
            num_layers=p.num_layers, input_dim=p.model_dim,
            transformer_layer_params_tpl=tl))
    self.CreateChild(
        "proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.model_dim, output_dim=p.output_dim,
            activation="NONE"))

  def FProp(self, theta, ids, paddings=None):
    """ids: [B, T] int32; paddings: optional [B, T] (1 = pad)."""
    if paddings is None:
      paddings = jnp.zeros(ids.shape, jnp.float32)
    x = self.emb.FProp(theta.emb, ids)
    # stateless sinusoidal embedding: no vars, so no theta entry
    x = x + self.pos_emb.FProp(NestedMap(),
                               seq_length=ids.shape[1])[None].astype(x.dtype)
    x = self.stack.FProp(theta.stack, x, paddings)
    w = (1.0 - paddings).astype(x.dtype)[:, :, None]
    pooled = jnp.sum(x * w, axis=1) / jnp.maximum(
        jnp.sum(w, axis=1), 1.0)
    return self.proj.FProp(theta.proj, pooled)
