"""Milan configs (ref `lingvo/tasks/milan/params/*`)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.milan import dual_encoder
from lingvo_tpu.models.milan import input_generator


@model_registry.RegisterSingleTaskModel
class MilanDualEncoder(base_model_params.SingleTaskModelParams):

  BATCH_SIZE = 64
  IMAGE_DIM = 64
  TEXT_DIM = 48
  EMB_DIM = 128

  def Train(self):
    return input_generator.SyntheticPairedInput.Params().Set(
        batch_size=self.BATCH_SIZE, image_dim=self.IMAGE_DIM,
        text_dim=self.TEXT_DIM)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    p = dual_encoder.DualEncoderTask.Params()
    p.name = "milan"
    p.image_encoder.input_dim = self.IMAGE_DIM
    p.image_encoder.output_dim = self.EMB_DIM
    p.text_encoder.input_dim = self.TEXT_DIM
    p.text_encoder.output_dim = self.EMB_DIM
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.Constant.Params())
    p.train.tpu_steps_per_loop = 50
    return p
