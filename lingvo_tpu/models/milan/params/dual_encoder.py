"""Milan configs (ref `lingvo/tasks/milan/params/*`)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.milan import dual_encoder
from lingvo_tpu.models.milan import encoders
from lingvo_tpu.models.milan import input_generator


@model_registry.RegisterSingleTaskModel
class MilanDualEncoder(base_model_params.SingleTaskModelParams):

  BATCH_SIZE = 64
  IMAGE_DIM = 64
  TEXT_DIM = 48
  EMB_DIM = 128

  def Train(self):
    return input_generator.SyntheticPairedInput.Params().Set(
        batch_size=self.BATCH_SIZE, image_dim=self.IMAGE_DIM,
        text_dim=self.TEXT_DIM)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    p = dual_encoder.DualEncoderTask.Params()
    p.name = "milan"
    p.image_encoder.input_dim = self.IMAGE_DIM
    p.image_encoder.output_dim = self.EMB_DIM
    p.text_encoder.input_dim = self.TEXT_DIM
    p.text_encoder.output_dim = self.EMB_DIM
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.Constant.Params())
    p.train.tpu_steps_per_loop = 50
    return p


@model_registry.RegisterSingleTaskModel
class MilanImageText(base_model_params.SingleTaskModelParams):
  """Real modality towers: conv image encoder + transformer text encoder
  over synthetic sprite images (ref `tasks/milan/params/cxc.py` shape:
  image tower + text transformer into a joint space)."""

  BATCH_SIZE = 32
  IMAGE_SIZE = 16
  NUM_SPRITES = 16
  TEXT_LEN = 6
  EMB_DIM = 64

  def Train(self):
    return input_generator.SyntheticImageTextInput.Params().Set(
        batch_size=self.BATCH_SIZE, image_size=self.IMAGE_SIZE,
        num_sprites=self.NUM_SPRITES, text_len=self.TEXT_LEN)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    p = dual_encoder.DualEncoderTask.Params()
    p.name = "milan_image_text"
    p.image_encoder = encoders.ConvImageEncoder.Params().Set(
        filter_counts=[32, 64], output_dim=self.EMB_DIM)
    p.text_encoder = encoders.TransformerTextEncoder.Params().Set(
        vocab_size=self.NUM_SPRITES + 1, model_dim=64, num_layers=2,
        num_heads=4, output_dim=self.EMB_DIM)
    p.image_input_features = "image"
    p.text_input_features = ("text_ids", "text_paddings")
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.Constant.Params())
    p.train.tpu_steps_per_loop = 50
    return p


@model_registry.RegisterSingleTaskModel
class MilanImageTextFiles(MilanImageText):
  """Same towers over the file-backed input (native record yielder); point
  file_pattern at JSON-lines records (see MilanFileInput docstring)."""

  FILE_PATTERN = "text:/tmp/milan/*.jsonl"

  def Train(self):
    return input_generator.MilanFileInput.Params().Set(
        batch_size=self.BATCH_SIZE, image_size=self.IMAGE_SIZE,
        text_len=self.TEXT_LEN, file_pattern=self.FILE_PATTERN)

  def Test(self):
    return self.Train()
