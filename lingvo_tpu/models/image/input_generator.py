"""MNIST-style input: real-file loader + synthetic fallback.

Ref `lingvo/tasks/image/input_generator.py` + `BaseTinyDatasetInput`
(`base_input_generator.py:1706`): the reference reads a ckpt of MNIST arrays
prepared by `keras2ckpt.py`. Here: `MnistFileInput` loads an .npz with the
same contents; `SyntheticMnistInput` procedurally generates a learnable
10-class digit-like dataset (class prototypes + noise) for hermetic tests and
benchmarks with no data egress.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


def _MakeSyntheticMnist(n: int, seed: int = 0, proto_seed: int = 0):
  """10 fixed prototypes (28x28) + noise; labels recoverable => learnable.

  Prototypes depend only on proto_seed so train/test splits share the same
  class structure; `seed` drives the per-split sampling noise.
  """
  protos = np.random.RandomState(proto_seed).rand(10, 28, 28, 1).astype(
      np.float32)
  rng = np.random.RandomState(seed + 1000003)
  labels = rng.randint(0, 10, n).astype(np.int32)
  images = protos[labels] + 0.3 * rng.randn(n, 28, 28, 1).astype(np.float32)
  return images.astype(np.float32), labels


class SyntheticMnistInput(base_input_generator.InMemoryInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.batch_size = 128
    p.num_samples = 5000
    p.Define("data_seed", 0, "Prototype/noise seed.")
    return p

  def __init__(self, params):
    params = params.Copy()
    images, labels = _MakeSyntheticMnist(params.num_samples, params.data_seed)
    params.data = NestedMap(image=images, label=labels)
    super().__init__(params)


class MnistFileInput(base_input_generator.InMemoryInputGenerator):
  """Loads an npz with arrays image [N,28,28,1] float32 and label [N] int32."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.batch_size = 128
    p.Define("ckpt", "", "Path to .npz file.")
    p.Define("split", "train", "train|test arrays prefix in the npz.")
    return p

  def __init__(self, params):
    params = params.Copy()
    blob = np.load(params.ckpt)
    images = blob[f"{params.split}_images"].astype(np.float32)
    if images.ndim == 3:
      images = images[..., None]
    if images.max() > 1.5:
      images = images / 255.0
    labels = blob[f"{params.split}_labels"].astype(np.int32)
    params.data = NestedMap(image=images, label=labels)
    params.num_samples = len(labels)
    super().__init__(params)
