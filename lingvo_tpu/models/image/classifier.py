"""Image classifier task (ref: lingvo/tasks/image/classifier.py).

`ModelV2`-style: conv tower + FC + softmax over [b, h, w, c] images with
integer labels. The canonical config is LeNet5 on MNIST
(ref `tasks/image/params/mnist.py:46`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers
from lingvo_tpu.core.nested_map import NestedMap


class BaseClassifier(base_model.BaseTask):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("softmax", layers.SimpleFullSoftmax.Params(), "Softmax tpl.")
    p.Define("dropout_prob", 0.0, "Dropout before softmax.")
    return p

  def _AddAccuracyMetrics(self, metrics, logits, labels, weight):
    acc1 = jnp.mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32))
    top5 = jax.lax.top_k(logits, min(5, logits.shape[-1]))[1]
    acc5 = jnp.mean(
        jnp.any(top5 == labels[:, None], axis=-1).astype(jnp.float32))
    metrics.accuracy = (acc1, weight)
    metrics.acc5 = (acc5, weight)
    return metrics


class ModelV2(BaseClassifier):
  """Conv tower classifier (ref classifier.py ModelV2)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("extract", None, "Conv feature extractor params list.")
    p.Define("label_smoothing", 0.0, "Label smoothing.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChildren("extract", list(p.extract or []))
    self.CreateChild("softmax", p.softmax)
    if p.dropout_prob > 0:
      self.CreateChild("dropout",
                       layers.DeterministicDropoutLayer.Params().Set(
                           keep_prob=1.0 - p.dropout_prob))

  def ComputePredictions(self, theta, input_batch):
    p = self.p
    x = input_batch.image
    for i, layer in enumerate(self.extract):
      out = layer.FProp(theta.extract[i], x)
      x = out[0] if isinstance(out, tuple) else out
    x = x.reshape(x.shape[0], -1)
    if p.dropout_prob > 0:
      x = self.dropout.FProp(self.ChildTheta(theta, "dropout"), x)
    xent = self.softmax.FProp(
        theta.softmax, x, class_ids=input_batch.label,
        label_smoothing=p.label_smoothing)
    return NestedMap(logits=xent.logits, per_example_xent=xent.per_example_xent)

  def ComputeLoss(self, theta, predictions, input_batch):
    batch = predictions.per_example_xent.shape[0]
    loss = jnp.mean(predictions.per_example_xent)
    metrics = NestedMap(
        loss=(loss, float(batch)),
        log_pplx=(loss, float(batch)))
    self._AddAccuracyMetrics(metrics, predictions.logits, input_batch.label,
                             float(batch))
    per_example = NestedMap(xent=predictions.per_example_xent)
    return metrics, per_example

  def Decode(self, theta, input_batch):
    preds = self.ComputePredictions(theta, input_batch)
    return NestedMap(
        predicted=jnp.argmax(preds.logits, -1),
        label=input_batch.label)

  def Inference(self):
    """'classify' subgraph: image -> class probs + argmax."""
    example = NestedMap(image=jnp.zeros((1, 28, 28, 1), jnp.float32),
                        label=jnp.zeros((1,), jnp.int32))

    def classify_fn(theta, inputs):
      from lingvo_tpu.core import py_utils
      with py_utils.EvalContext():
        preds = self.ComputePredictions(theta, inputs)
      probs = jax.nn.softmax(preds.logits.astype(jnp.float32), -1)
      return NestedMap(probs=probs, predicted=jnp.argmax(probs, -1))

    return {"classify": (classify_fn, example)}

  def CreateDecoderMetrics(self):
    from lingvo_tpu.core import metrics as metrics_lib
    return {"accuracy": metrics_lib.AverageMetric()}

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    import numpy as np
    correct = (decode_out.predicted == decode_out.label).astype("float32")
    decoder_metrics["accuracy"].Update(float(correct.mean()),
                                       len(decode_out.label))
