"""MNIST experiment configs (ref: lingvo/tasks/image/params/mnist.py:46)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import layers
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.models.image import classifier
from lingvo_tpu.models.image import input_generator


@model_registry.RegisterSingleTaskModel
class LeNet5(base_model_params.SingleTaskModelParams):
  """LeNet-5 on (synthetic) MNIST; target: loss <0.3, acc >= 0.94."""

  BATCH_SIZE = 128

  def Train(self):
    return input_generator.SyntheticMnistInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_samples=50000, data_seed=0)

  def Test(self):
    return input_generator.SyntheticMnistInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_samples=5000, data_seed=1,
        shuffle=False, repeat=False, require_sequential_order=True)

  def Task(self):
    p = classifier.ModelV2.Params()
    p.name = "lenet5"
    # Conv tower: 5x5x20 -> pool -> 5x5x50 -> pool (classic LeNet5 shapes).
    p.extract = [
        layers.Conv2DLayer.Params().Set(
            filter_shape=(5, 5, 1, 20), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True),
        layers.MaxPoolLayer.Params().Set(
            window_shape=(2, 2), window_stride=(2, 2)),
        layers.Conv2DLayer.Params().Set(
            filter_shape=(5, 5, 20, 50), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True),
        layers.MaxPoolLayer.Params().Set(
            window_shape=(2, 2), window_stride=(2, 2)),
    ]
    p.softmax = layers.SimpleFullSoftmax.Params().Set(
        input_dim=7 * 7 * 50, num_classes=10)
    p.dropout_prob = 0.2
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3, optimizer=opt_lib.Adam.Params(),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 20
    p.train.max_steps = 400
    p.train.save_interval_steps = 200
    return p
