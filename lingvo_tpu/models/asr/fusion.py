"""LM fusion for ASR decoding (ref `lingvo/tasks/asr/fusion.py`
FusionBase:23 / NullFusion:173).

Shallow fusion combines the acoustic model's per-step distribution with an
external language model's at DECODE time only:
  log p(y_t) = log p_am(y_t) + lm_weight * log p_lm(y_t)
The LM state rides inside the decoder's beam-search state pytree, so beam
reordering (`beam_search._GatherBeams`) keeps each hypothesis's LM context
consistent — the TPU-native equivalent of the reference's fused
PreBeamSearchStepCallback.

Any layer exposing `FusionInit(theta, batch) -> state` and
`FusionStep(theta, state, prev_ids) -> (logits, state)` can serve as the
LM; `RnnLmForFusion` is the built-in one (embedding + LSTM stack + proj).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core.nested_map import NestedMap


class RnnLmForFusion(base_layer.BaseLayer):
  """Step-oriented RNN LM: per-token scoring with carried LSTM state."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 0, "Vocab (must match the AM's).")
    p.Define("emb_dim", 64, "Embedding dim.")
    p.Define("rnn_dim", 128, "LSTM hidden dim.")
    p.Define("num_layers", 1, "LSTM stack depth.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    assert p.vocab_size > 0
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.emb_dim))
    cells = []
    for i in range(p.num_layers):
      cells.append(rnn_cell.LSTMCellSimple.Params().Set(
          num_input_nodes=p.emb_dim if i == 0 else p.rnn_dim,
          num_output_nodes=p.rnn_dim))
    self.CreateChildren("rnn", cells)
    self.CreateChild(
        "proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.rnn_dim, output_dim=p.vocab_size))

  def FusionInit(self, theta, batch_size: int) -> NestedMap:
    del theta
    return NestedMap(rnn=[c.InitState(batch_size) for c in self.rnn])

  def FusionStep(self, theta, state, prev_ids):
    """prev_ids [B] -> (logits [B, V], new state)."""
    x = self.emb.EmbLookup(self.ChildTheta(theta, "emb"),
                           prev_ids[:, None])[:, 0]
    new_rnn = []
    for i, cell in enumerate(self.rnn):
      st = cell.FProp(theta.rnn[i], state.rnn[i], x)
      new_rnn.append(st)
      x = cell.GetOutput(st)
    logits = self.proj.FProp(theta.proj, x)
    return logits, NestedMap(rnn=new_rnn)


class FusionBase(base_layer.BaseLayer):
  """Fusion interface (ref FusionBase:23)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("lm", None, "LM layer params (FusionInit/FusionStep surface).")
    p.Define("lm_weight", 0.3, "LM interpolation weight at decode.")
    return p

  def __init__(self, params):
    super().__init__(params)
    if self.p.lm is not None:
      self.CreateChild("lm", self.p.lm)

  def InitState(self, theta, batch_size: int) -> NestedMap:
    return NestedMap()

  def FuseLogits(self, theta, state, prev_ids, am_logits):
    """-> (fused log-space scores [B, V], new fusion state)."""
    raise NotImplementedError


class NullFusion(FusionBase):
  """No-op fusion (ref NullFusion:173): AM scores pass through."""

  def FuseLogits(self, theta, state, prev_ids, am_logits):
    del prev_ids
    return am_logits, state


class ShallowFusion(FusionBase):
  """log p_am + w * log p_lm (decode-time only, the standard recipe)."""

  def __init__(self, params):
    super().__init__(params)
    assert self.p.lm is not None, "ShallowFusion needs an lm template"

  def InitState(self, theta, batch_size: int) -> NestedMap:
    return NestedMap(
        lm=self.lm.FusionInit(self.ChildTheta(theta, "lm"), batch_size))

  def FuseLogits(self, theta, state, prev_ids, am_logits):
    lm_logits, lm_state = self.lm.FusionStep(
        self.ChildTheta(theta, "lm"), state.lm, prev_ids)
    fused = (jax.nn.log_softmax(am_logits.astype(jnp.float32), -1) +
             self.p.lm_weight *
             jax.nn.log_softmax(lm_logits.astype(jnp.float32), -1))
    return fused, NestedMap(lm=lm_state)
