"""Synthetic ASR input: per-token tone features, learnable waveform->text.

Ref shape contract: `tasks/asr/input_generator.py` AsrInput (src features +
tgt token ids). Each label token renders as a characteristic feature pattern
over a few frames, so a conformer-CTC model can learn the mapping quickly
and WER is a meaningful signal without shipping audio data.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class SyntheticAsrInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_bins", 80, "Feature bins.")
    p.Define("max_label_len", 12, "Max tokens per utterance.")
    p.Define("frames_per_token", 8, "Feature frames per token.")
    p.Define("vocab_size", 30, "Token vocab (blank=0 excluded from labels).")
    p.Define("noise", 0.2, "Feature noise stddev.")
    p.Define("seed", 0, "Seed.")
    p.Define("teacher_forcing", False,
             "LAS layout: tgt.ids sos-prefixed + tgt.labels eos-suffixed "
             "(content ids 3..vocab); else CTC layout (ids >= 1).")
    p.Define("sos_id", 1, "SOS (teacher_forcing).")
    p.Define("eos_id", 2, "EOS (teacher_forcing).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    # one fixed feature prototype per token id (seed shared across splits so
    # train/test see the same token->feature mapping)
    self._protos = np.random.RandomState(777).randn(
        p.vocab_size, p.num_bins).astype(np.float32)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 31337 * self._step) % (2**31))
    self._step += 1
    b = p.batch_size
    max_frames = p.max_label_len * p.frames_per_token
    feats = np.zeros((b, max_frames, p.num_bins), np.float32)
    fpad = np.ones((b, max_frames), np.float32)
    ids = np.zeros((b, p.max_label_len), np.int32)
    lpad = np.ones((b, p.max_label_len), np.float32)
    labels = np.zeros((b, p.max_label_len), np.int32)
    for i in range(b):
      if p.teacher_forcing:
        # LAS layout: content ids 3.. ; ids=[sos, w...], labels=[w..., eos]
        n = rng.randint(2, p.max_label_len)
        toks = rng.randint(3, p.vocab_size, n)
        ids[i, 0] = p.sos_id
        ids[i, 1:n + 1] = toks
        labels[i, :n] = toks
        labels[i, n] = p.eos_id
        lpad[i, :n + 1] = 0.0
      else:
        n = rng.randint(2, p.max_label_len + 1)
        toks = rng.randint(1, p.vocab_size, n)  # 0 reserved for blank
        ids[i, :n] = toks
        lpad[i, :n] = 0.0
      for j, tok in enumerate(toks):
        s = j * p.frames_per_token
        feats[i, s:s + p.frames_per_token] = self._protos[tok]
      t = n * p.frames_per_token
      feats[i, :t] += p.noise * rng.randn(t, p.num_bins)
      fpad[i, :t] = 0.0
    tgt = NestedMap(ids=ids, paddings=lpad)
    if p.teacher_forcing:
      tgt.labels = labels
    return NestedMap(features=feats, feature_paddings=fpad, tgt=tgt)


class AsrRecordInput(base_input_generator.FileBasedSequenceInputGenerator):
  """Real-data ASR input over featurized recordio shards (the output of
  tools/create_asr_features.py): JSON records with 'features' [t, bins] and
  'transcript', bucketed by frame count; transcripts tokenized by
  p.tokenizer (grapheme/WPM — ids must leave 0 free for CTC blank).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_bins", 80, "Feature dim (records must match).")
    p.Define("max_label_len", 64, "Max transcript tokens.")
    p.bucket_upper_bound = [400, 800, 1600]
    p.bucket_batch_limit = [32, 16, 8]
    return p

  def ProcessRecord(self, record: bytes):
    import json
    p = self.p
    try:
      rec = json.loads(record)
    except ValueError:
      return None
    feats = np.asarray(rec["features"], np.float32)
    if feats.ndim != 2 or feats.shape[1] != p.num_bins or not feats.size:
      return None
    t = feats.shape[0]
    if t > p.bucket_upper_bound[-1]:
      return None
    _, label_ids, label_pads = self.StringsToIds([rec["transcript"]],
                                                 p.max_label_len)
    n = int((1.0 - label_pads[0]).sum())
    if n < 1:
      return None
    return NestedMap(
        features=feats,
        feature_paddings=np.zeros(t, np.float32),
        tgt=NestedMap(ids=label_ids[0][:n],
                      paddings=label_pads[0][:n]),
        bucket_key=t)
