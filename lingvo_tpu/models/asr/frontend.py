"""ASR frontend: waveform -> log-mel features, fully in jnp (on-device).

Re-designs `lingvo/tasks/asr/frontend.py` (MelAsrFrontend): framing, Hann
window, rFFT power spectrum, mel filterbank, log compression. Runs under jit
on TPU (the reference computes this in the input pipeline on CPU).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import py_utils


def _HzToMel(hz):
  return 2595.0 * np.log10(1.0 + hz / 700.0)


def _MelToHz(mel):
  return 700.0 * (10.0**(mel / 2595.0) - 1.0)


def MelFilterbank(num_bins: int, fft_size: int, sample_rate: float,
                  lower_edge_hz: float = 125.0,
                  upper_edge_hz: float = 7600.0) -> np.ndarray:
  """[fft_size//2+1, num_bins] triangular mel weights."""
  num_spectrogram_bins = fft_size // 2 + 1
  fft_freqs = np.linspace(0, sample_rate / 2, num_spectrogram_bins)
  mel_edges = np.linspace(
      _HzToMel(lower_edge_hz), _HzToMel(upper_edge_hz), num_bins + 2)
  hz_edges = _MelToHz(mel_edges)
  weights = np.zeros((num_spectrogram_bins, num_bins), np.float32)
  for i in range(num_bins):
    lower, center, upper = hz_edges[i:i + 3]
    up_slope = (fft_freqs - lower) / max(center - lower, 1e-8)
    down_slope = (upper - fft_freqs) / max(upper - center, 1e-8)
    weights[:, i] = np.maximum(0.0, np.minimum(up_slope, down_slope))
  return weights


class MelAsrFrontend(base_layer.BaseLayer):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("sample_rate", 16000.0, "Hz.")
    p.Define("frame_size_ms", 25.0, "Window size.")
    p.Define("frame_step_ms", 10.0, "Hop size.")
    p.Define("num_bins", 80, "Mel bins.")
    p.Define("lower_edge_hz", 125.0, "Mel low edge.")
    p.Define("upper_edge_hz", 7600.0, "Mel high edge.")
    return p

  def _NameIsRequired(self):
    return False

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self._frame_size = int(round(p.sample_rate * p.frame_size_ms / 1000.0))
    self._frame_step = int(round(p.sample_rate * p.frame_step_ms / 1000.0))
    self._fft_size = int(2**math.ceil(math.log2(self._frame_size)))
    self._mel = jnp.asarray(
        MelFilterbank(p.num_bins, self._fft_size, p.sample_rate,
                      p.lower_edge_hz, p.upper_edge_hz))
    self._window = jnp.asarray(
        np.hanning(self._frame_size).astype(np.float32))

  @property
  def frame_step(self):
    return self._frame_step

  def FProp(self, theta, waveform, paddings=None):
    """waveform: [b, samples] -> (features [b, frames, num_bins],
    out_paddings [b, frames])."""
    b, n = waveform.shape
    if n < self._frame_size:  # zero-pad short clips to one full frame
      waveform = jnp.pad(waveform, ((0, 0), (0, self._frame_size - n)))
      if paddings is not None:
        paddings = jnp.pad(paddings, ((0, 0), (0, self._frame_size - n)),
                           constant_values=1.0)
      n = self._frame_size
    num_frames = max(1 + (n - self._frame_size) // self._frame_step, 1)
    idx = (jnp.arange(num_frames)[:, None] * self._frame_step +
           jnp.arange(self._frame_size)[None, :])
    frames = waveform[:, idx]                       # [b, frames, frame_size]
    frames = frames * self._window
    spec = jnp.fft.rfft(frames, n=self._fft_size, axis=-1)
    power = jnp.square(jnp.abs(spec)).astype(jnp.float32)
    mel = jnp.einsum("btf,fm->btm", power, self._mel)
    logmel = jnp.log(jnp.maximum(mel, 1e-6))
    if paddings is not None:
      frame_pad = paddings[:, idx[:, 0]]
      logmel = py_utils.ApplyPadding(frame_pad, logmel)
      return logmel, frame_pad
    return logmel, jnp.zeros((b, num_frames), jnp.float32)
