"""LAS attention decoder (ref `lingvo/tasks/asr/decoder.py`
AsrDecoderBase/Decoder: embed previous label, stacked LSTMs where layer 0
consumes [emb, context], per-step seq attention over the encoder, logits
from [rnn_out, context]).

TPU-first shape: teacher forcing is one `lax.scan` over target time (the
reference's `recurrent.Recurrent` custom-gradient while-loop collapses into
scan + autodiff); beam-search decode reuses the same per-step function
through the flat BeamSearchHelper with coverage penalty.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import beam_search as beam_search_lib
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core import seq_attention
from lingvo_tpu.core.nested_map import NestedMap


class LasDecoder(base_layer.BaseLayer):
  """Attention decoder over encoder outputs."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 77, "Output vocab (sos/eos included).")
    p.Define("emb_dim", 96, "Label embedding dim.")
    p.Define("rnn_cell_dim", 256, "LSTM hidden dim.")
    p.Define("num_rnn_layers", 2, "Stacked LSTM depth.")
    p.Define("rnn_cell_tpl", rnn_cell.LSTMCellSimple.Params(),
             "Decoder cell template.")
    p.Define("attention", seq_attention.LocationSensitiveAttention.Params(),
             "Seq attention template (ref LocationSensitiveAttention:2334).")
    p.Define("source_dim", 256, "Encoder output dim.")
    p.Define("label_smoothing", 0.1, "Label smoothing epsilon.")
    p.Define("target_sos_id", 1, "SOS.")
    p.Define("target_eos_id", 2, "EOS.")
    p.Define("beam_search", beam_search_lib.BeamSearchHelper.Params().Set(
        num_hyps_per_beam=8, coverage_penalty=0.2), "Beam search.")
    p.Define("fusion", None,
             "Optional LM fusion params (models/asr/fusion.py, ref "
             "tasks/asr/fusion.py); applied at beam-search decode only.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.emb_dim))
    cells = []
    for i in range(p.num_rnn_layers):
      in_dim = (p.emb_dim + p.source_dim) if i == 0 else p.rnn_cell_dim
      cells.append(p.rnn_cell_tpl.Copy().Set(
          num_input_nodes=in_dim, num_output_nodes=p.rnn_cell_dim))
    self.CreateChildren("rnn", cells)
    self.CreateChild(
        "atten",
        p.attention.Copy().Set(
            source_dim=p.source_dim, query_dim=p.rnn_cell_dim,
            hidden_dim=p.attention.hidden_dim or p.rnn_cell_dim))
    self.CreateChild(
        "softmax",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.rnn_cell_dim + p.source_dim,
            output_dim=p.vocab_size))
    if p.fusion is not None:
      self.CreateChild("fusion", p.fusion)

  # -- per-step core ---------------------------------------------------------
  def _InitStates(self, theta, batch_size: int, src_len: int) -> NestedMap:
    p = self.p
    return NestedMap(
        rnn=[c.InitState(batch_size) for c in self.rnn],
        atten=self.atten.ZeroAttentionState(batch_size, src_len),
        context=jnp.zeros((batch_size, p.source_dim), self.fprop_dtype))

  def _Step(self, theta, packed, prev_ids, states):
    """One decode step: prev_ids [B] -> (logits [B, V], probs, new states)."""
    emb = self.emb.EmbLookup(self.ChildTheta(theta, "emb"), prev_ids[:, None])
    emb = emb[:, 0]                                       # [B, E]
    x = jnp.concatenate([emb, states.context.astype(emb.dtype)], axis=-1)
    new_rnn = []
    for i, cell in enumerate(self.rnn):
      st = cell.FProp(theta.rnn[i], states.rnn[i], x)
      new_rnn.append(st)
      x = cell.GetOutput(st)
    query = x                                             # [B, H]
    ctx, probs, new_atten = self.atten.ComputeContextVector(
        self.ChildTheta(theta, "atten"), packed, query, states.atten)
    logits = self.softmax.FProp(
        theta.softmax,
        jnp.concatenate([query, ctx.astype(query.dtype)], axis=-1))
    new_states = NestedMap(rnn=new_rnn, atten=new_atten, context=ctx)
    return logits, probs, new_states

  # -- training --------------------------------------------------------------
  def ComputeLogits(self, theta, encoded, enc_paddings, tgt_ids):
    """Teacher forcing: tgt_ids [B, T] (sos-prefixed) ->
    (logits [B, T, V], atten_probs [B, T, T_src])."""
    b, t = tgt_ids.shape
    packed = self.atten.PackSource(
        self.ChildTheta(theta, "atten"), encoded, enc_paddings)
    states0 = self._InitStates(theta, b, encoded.shape[1])

    def _Body(states, ids_t):
      logits, probs, new_states = self._Step(theta, packed, ids_t, states)
      return new_states, (logits, probs)

    _, (logits, probs) = jax.lax.scan(_Body, states0, tgt_ids.swapaxes(0, 1))
    return logits.swapaxes(0, 1), probs.swapaxes(0, 1)    # [B,T,V], [B,T,S]

  def ComputeLoss(self, theta, logits, tgt):
    """Smoothed xent against tgt.labels with tgt.paddings weighting."""
    p = self.p
    xent = layers_lib.XentLossFromLogits(
        logits, p.vocab_size, class_ids=tgt.labels,
        label_smoothing=p.label_smoothing).per_example_xent
    weights = 1.0 - tgt.paddings
    tot = jnp.maximum(jnp.sum(weights), 1e-8)
    loss = jnp.sum(xent * weights) / tot
    acc = jnp.sum(
        (jnp.argmax(logits, -1) == tgt.labels) * weights) / tot
    return loss, acc, tot

  # -- decoding --------------------------------------------------------------
  def BeamSearchDecode(self, theta, encoded, enc_paddings) -> NestedMap:
    p = self.p
    b, src_len = encoded.shape[0], encoded.shape[1]
    k = p.beam_search.num_hyps_per_beam
    helper = p.beam_search.Copy().Set(
        target_sos_id=p.target_sos_id,
        target_eos_id=p.target_eos_id).Instantiate()

    def _Tile(x):
      return jnp.repeat(x, k, axis=0)

    # pack ONCE on [B, T, D], then tile the packed projections to the beams
    packed = self.atten.PackSource(
        self.ChildTheta(theta, "atten"), encoded, enc_paddings)
    packed = jax.tree_util.tree_map(_Tile, packed)
    init = self._InitStates(theta, b * k, src_len)
    if p.fusion is not None:
      # LM state lives in the beam states so parent-gathering keeps each
      # hypothesis's LM context aligned with its token history
      init.fusion = self.fusion.InitState(
          self.ChildTheta(theta, "fusion"), b * k)

    def _StepFn(states, ids):
      logits, probs, new_states = self._Step(theta, packed, ids[:, 0],
                                             states)
      if p.fusion is not None:
        logits, new_states.fusion = self.fusion.FuseLogits(
            self.ChildTheta(theta, "fusion"), states.fusion, ids[:, 0],
            logits)
      return logits, new_states, probs

    return helper.Search(b, init, _StepFn, src_len=src_len,
                         src_paddings=enc_paddings)
