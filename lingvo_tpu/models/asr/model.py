"""ASR task: Conformer-CTC (ref: lingvo/tasks/asr encoder/decoder stack).

Pipeline: (waveform -> log-mel | precomputed features) -> SpecAugment ->
conv subsampling -> conformer stack -> CTC loss; greedy CTC decode + WER.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_model
from lingvo_tpu.core import conformer_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import spectrum_augmenter
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.asr import decoder_metrics as dm
from lingvo_tpu.models.asr import frontend as frontend_lib


class CtcAsrModel(base_model.BaseTask):
  """Conformer encoder + CTC head.

  Input batch: either waveform [b, samples] (+paddings) or features
  [b, t, num_bins] (+feature_paddings); labels: tgt.ids [b, l] with
  tgt.paddings. Blank id = 0; label ids must be >= 1.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("frontend", frontend_lib.MelAsrFrontend.Params(),
             "Waveform frontend (unused when features are fed directly).")
    p.Define("specaug", spectrum_augmenter.SpectrumAugmenter.Params(),
             "SpecAugment.")
    p.Define("input_dim", 80, "Feature dim.")
    p.Define("model_dim", 256, "Conformer dim.")
    p.Define("num_layers", 16, "Conformer depth.")
    p.Define("num_heads", 4, "Attention heads.")
    p.Define("kernel_size", 32, "LConv kernel.")
    p.Define("vocab_size", 77, "Output vocab incl. blank at 0.")
    p.Define("subsample_factor", 4, "Time subsampling (2 conv stride-2).")
    p.Define("dropout_prob", 0.0, "Dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild("frontend", p.frontend)
    self.CreateChild("specaug", p.specaug)
    # conv subsampling: two stride-2 convs over time
    self.CreateChild(
        "sub1",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, 1, 32), filter_stride=(2, 2),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "sub2",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, 32, 32), filter_stride=(2, 2),
            activation="RELU", batch_norm=False, has_bias=True))
    # two SAME stride-2 convs: freq -> ceil(ceil(f/2)/2)
    sub_freq = (p.input_dim + 1) // 2
    sub_freq = (sub_freq + 1) // 2
    self.CreateChild(
        "input_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=32 * sub_freq, output_dim=p.model_dim))
    blocks = []
    for _ in range(p.num_layers):
      blocks.append(conformer_layer.ConformerLayer.Params().Set(
          input_dim=p.model_dim, atten_num_heads=p.num_heads,
          kernel_size=p.kernel_size, dropout_prob=p.dropout_prob))
    self.CreateChildren("conformer", blocks)
    self.CreateChild(
        "ctc_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.model_dim, output_dim=p.vocab_size))

  def _Encode(self, theta, input_batch):
    p = self.p
    if "features" in input_batch:
      feats = input_batch.features
      fpad = input_batch.Get("feature_paddings")
      if fpad is None:
        fpad = jnp.zeros(feats.shape[:2], jnp.float32)
    else:
      feats, fpad = self.frontend.FProp(
          self.ChildTheta(theta, "frontend"), input_batch.waveform,
          input_batch.Get("paddings"))
    feats = self.specaug.FProp(self.ChildTheta(theta, "specaug"), feats,
                               fpad)
    x = feats[..., None]                     # [b, t, f, 1]
    x, fpad = self.sub1.FProp(theta.sub1, x, fpad)
    x, fpad = self.sub2.FProp(theta.sub2, x, fpad)
    b, t = x.shape[0], x.shape[1]
    x = x.reshape(b, t, -1)
    x = self.input_proj.FProp(theta.input_proj, x)
    for i, block in enumerate(self.conformer):
      x = block.FProp(theta.conformer[i], x, fpad)
    logits = self.ctc_proj.FProp(theta.ctc_proj, x)
    return logits, fpad

  def ComputePredictions(self, theta, input_batch):
    logits, out_paddings = self._Encode(theta, input_batch)
    return NestedMap(logits=logits, paddings=out_paddings)

  def ComputeLoss(self, theta, predictions, input_batch):
    import optax
    labels = input_batch.tgt.ids
    label_paddings = input_batch.tgt.paddings
    per_seq = optax.ctc_loss(
        predictions.logits.astype(jnp.float32), predictions.paddings,
        labels, label_paddings, blank_id=0)
    label_counts = jnp.maximum(
        jnp.sum(1.0 - label_paddings, axis=-1), 1.0)
    num_seqs = float(labels.shape[0])
    avg = jnp.mean(per_seq / label_counts)
    metrics = NestedMap(loss=(avg, num_seqs))
    return metrics, NestedMap(ctc=per_seq)

  def Decode(self, theta, input_batch):
    logits, out_paddings = self._Encode(theta, input_batch)
    # greedy CTC: argmax frames (blank=0), collapse repeats, drop blanks
    frame_ids = jnp.argmax(logits, axis=-1)
    frame_ids = jnp.where(out_paddings > 0.5, 0, frame_ids)
    return NestedMap(
        frame_ids=frame_ids,
        target_ids=input_batch.tgt.ids,
        target_paddings=input_batch.tgt.paddings)

  def CreateDecoderMetrics(self):
    return {"wer": dm.WerMetric()}

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    frames = np.asarray(decode_out.frame_ids)
    labels = np.asarray(decode_out.target_ids)
    lpads = np.asarray(decode_out.target_paddings)
    for i in range(frames.shape[0]):
      hyp = []
      prev = 0
      for t in frames[i]:
        if t != 0 and t != prev:
          hyp.append(int(t))
        prev = t
      ref_len = int((1.0 - lpads[i]).sum())
      ref = [int(x) for x in labels[i, :ref_len]]
      decoder_metrics["wer"].Update(ref, hyp)

  def DecodeFinalize(self, decoder_metrics):
    return {"wer": decoder_metrics["wer"].value,
            "num_utterances": float(decoder_metrics["wer"].num_utterances)}
