"""ASR tasks: Conformer-CTC and LAS (ref: lingvo/tasks/asr).

Pipeline: (waveform -> log-mel | precomputed features) -> SpecAugment ->
conv subsampling -> conformer stack -> {CTC head | LAS attention decoder};
greedy CTC / beam-search LAS decode + WER (ref `tasks/asr/model.py`,
`tasks/asr/decoder.py`, `decoder_metrics.py`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import py_utils

from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.asr import decoder as las_decoder
from lingvo_tpu.models.asr import decoder_metrics as dm
from lingvo_tpu.models.asr import encoder as encoder_lib


class _AsrTaskBase(base_model.BaseTask):
  """Shared encoder construction + WER decode metrics."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("encoder", encoder_lib.AsrConformerEncoder.Params(),
             "Acoustic encoder.")
    p.Define("vocab_size", 77, "Output vocab.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("encoder", self.p.encoder)

  def _Encode(self, theta, input_batch):
    return self.encoder.FProp(self.ChildTheta(theta, "encoder"), input_batch)

  def CreateDecoderMetrics(self):
    return {"wer": dm.WerMetric()}

  def DecodeFinalize(self, decoder_metrics):
    return {"wer": decoder_metrics["wer"].value,
            "num_utterances": float(decoder_metrics["wer"].num_utterances)}


class CtcAsrModel(_AsrTaskBase):
  """Conformer encoder + CTC head.

  Input batch: either waveform [b, samples] (+paddings) or features
  [b, t, num_bins] (+feature_paddings); labels: tgt.ids [b, l] with
  tgt.paddings. Blank id = 0; label ids must be >= 1.
  """

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild(
        "ctc_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=self.p.encoder.model_dim,
            output_dim=self.p.vocab_size))

  def ComputePredictions(self, theta, input_batch):
    x, out_paddings = self._Encode(theta, input_batch)
    logits = self.ctc_proj.FProp(theta.ctc_proj, x)
    return NestedMap(logits=logits, paddings=out_paddings)

  def ComputeLoss(self, theta, predictions, input_batch):
    import optax
    labels = input_batch.tgt.ids
    label_paddings = input_batch.tgt.paddings
    per_seq = optax.ctc_loss(
        predictions.logits.astype(jnp.float32), predictions.paddings,
        labels, label_paddings, blank_id=0)
    label_counts = jnp.maximum(
        jnp.sum(1.0 - label_paddings, axis=-1), 1.0)
    num_seqs = float(labels.shape[0])
    avg = jnp.mean(per_seq / label_counts)
    metrics = NestedMap(loss=(avg, num_seqs))
    return metrics, NestedMap(ctc=per_seq)

  def Decode(self, theta, input_batch):
    predictions = self.ComputePredictions(theta, input_batch)
    # greedy CTC: argmax frames (blank=0), collapse repeats, drop blanks
    frame_ids = jnp.argmax(predictions.logits, axis=-1)
    frame_ids = jnp.where(predictions.paddings > 0.5, 0, frame_ids)
    return NestedMap(
        frame_ids=frame_ids,
        target_ids=input_batch.tgt.ids,
        target_paddings=input_batch.tgt.paddings)

  def Inference(self):
    """'transcribe' subgraph: log-mel features -> greedy CTC frame ids
    (blank=0; host collapses repeats, ref PostProcessDecodeOut)."""
    bins = self.p.encoder.input_dim
    t = 96
    example = NestedMap(
        features=jnp.zeros((1, t, bins), jnp.float32),
        feature_paddings=jnp.zeros((1, t), jnp.float32))

    def transcribe_fn(theta, inputs):
      with py_utils.EvalContext():
        preds = self.ComputePredictions(theta, inputs)
      frame_ids = jnp.argmax(preds.logits, axis=-1)
      frame_ids = jnp.where(preds.paddings > 0.5, 0, frame_ids)
      return NestedMap(frame_ids=frame_ids, frame_paddings=preds.paddings)

    return {"transcribe": (transcribe_fn, example)}

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    frames = np.asarray(decode_out.frame_ids)
    labels = np.asarray(decode_out.target_ids)
    lpads = np.asarray(decode_out.target_paddings)
    for i in range(frames.shape[0]):
      hyp = []
      prev = 0
      for t in frames[i]:
        if t != 0 and t != prev:
          hyp.append(int(t))
        prev = t
      ref_len = int((1.0 - lpads[i]).sum())
      ref = [int(x) for x in labels[i, :ref_len]]
      decoder_metrics["wer"].Update(ref, hyp)


class LasAsrModel(_AsrTaskBase):
  """Conformer encoder + LAS attention decoder (ref `tasks/asr/decoder.py`;
  the reference's Librispeech configs are LAS, `librispeech.py:156,239`).

  Targets follow the teacher-forcing layout: tgt.ids sos-prefixed,
  tgt.labels eos-suffixed, tgt.paddings over labels.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("decoder", las_decoder.LasDecoder.Params(), "LAS decoder.")
    p.Define("alignment_summaries", False,
             "Also compute forced-alignment attention during Decode "
             "(rendered as images by DecodeProgram). Costs one extra "
             "teacher-forcing scan per decode batch — diagnostics only.")
    return p

  def __init__(self, params):
    p = params
    p.decoder.vocab_size = p.vocab_size
    p.decoder.source_dim = p.encoder.model_dim
    super().__init__(p)
    self.CreateChild("decoder", self.p.decoder)

  def ComputePredictions(self, theta, input_batch):
    encoded, enc_paddings = self._Encode(theta, input_batch)
    logits, atten_probs = self.decoder.ComputeLogits(
        self.ChildTheta(theta, "decoder"), encoded, enc_paddings,
        input_batch.tgt.ids)
    return NestedMap(logits=logits, atten_probs=atten_probs)

  def ComputeLoss(self, theta, predictions, input_batch):
    loss, acc, tot = self.decoder.ComputeLoss(
        self.ChildTheta(theta, "decoder"), predictions.logits,
        input_batch.tgt)
    num_seqs = float(input_batch.tgt.ids.shape[0])
    metrics = NestedMap(loss=(loss, num_seqs), accuracy=(acc, tot))
    return metrics, NestedMap()

  def Decode(self, theta, input_batch):
    encoded, enc_paddings = self._Encode(theta, input_batch)
    hyps = self.decoder.BeamSearchDecode(
        self.ChildTheta(theta, "decoder"), encoded, enc_paddings)
    out = NestedMap(
        topk_ids=hyps.topk_ids, topk_lens=hyps.topk_lens,
        topk_scores=hyps.topk_scores,
        target_labels=input_batch.tgt.labels,
        target_paddings=input_batch.tgt.paddings)
    if self.p.alignment_summaries:
      # forced-alignment attention on the reference targets: the classic
      # LAS alignment diagnostic (rendered as images by DecodeProgram)
      _, out.atten_probs = self.decoder.ComputeLogits(
          self.ChildTheta(theta, "decoder"), encoded, enc_paddings,
          input_batch.tgt.ids)
    return out

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    eos = self.p.decoder.target_eos_id
    best = np.asarray(decode_out.topk_ids)[:, 0]          # [B, T]
    lens = np.asarray(decode_out.topk_lens)[:, 0]
    labels = np.asarray(decode_out.target_labels)
    lpads = np.asarray(decode_out.target_paddings)
    for i in range(best.shape[0]):
      hyp = [int(x) for x in best[i, :int(lens[i])] if x != eos]
      ref_len = int((1.0 - lpads[i]).sum())
      ref = [int(x) for x in labels[i, :ref_len] if x != eos]
      decoder_metrics["wer"].Update(ref, hyp)
