"""Librispeech ASR configs (ref: lingvo/tasks/asr/params/librispeech.py
Librispeech960Grapheme:156 — grapheme LAS; here the modern Conformer-CTC
recipe at comparable scale, on synthetic input until the native pipeline
feeds real Librispeech tfrecords)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.asr import input_generator
from lingvo_tpu.models.asr import model as asr_model


@model_registry.RegisterSingleTaskModel
class Librispeech960ConformerCtc(base_model_params.SingleTaskModelParams):
  """Conformer-CTC at Librispeech-960 grapheme scale."""

  BATCH_SIZE = 16
  NUM_BINS = 80
  MODEL_DIM = 256
  NUM_LAYERS = 16
  NUM_HEADS = 4
  VOCAB = 77  # graphemes + blank (ref grapheme vocab size)

  def Train(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30))

  def Test(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30), seed=99)

  def Task(self):
    p = asr_model.CtcAsrModel.Params()
    p.name = "librispeech_ctc"
    p.encoder.input_dim = self.NUM_BINS
    p.encoder.model_dim = self.MODEL_DIM
    p.encoder.num_layers = self.NUM_LAYERS
    p.encoder.num_heads = self.NUM_HEADS
    p.vocab_size = self.VOCAB
    p.encoder.dropout_prob = 0.1
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=2.0,
        optimizer=opt_lib.AdamW.Params().Set(beta2=0.98, weight_decay=1e-6),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=10000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class LibrispeechConformerCtcTiny(Librispeech960ConformerCtc):
  """Smoke-test scale."""

  BATCH_SIZE = 4
  NUM_BINS = 16
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  VOCAB = 30

  def Task(self):
    p = super().Task()
    p.encoder.kernel_size = 8
    p.encoder.dropout_prob = 0.0
    p.encoder.specaug.freq_mask_max_bins = 4
    p.encoder.specaug.time_mask_max_frames = 8
    p.train.learner.learning_rate = 2e-3
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class Librispeech960GraphemeLas(base_model_params.SingleTaskModelParams):
  """Grapheme LAS (ref `librispeech.py:156` Librispeech960Grapheme — the
  reference's Librispeech configs are LAS attention models; conformer
  encoder + location-sensitive-attention LSTM decoder here)."""

  BATCH_SIZE = 16
  NUM_BINS = 80
  MODEL_DIM = 256
  NUM_LAYERS = 16
  NUM_HEADS = 4
  VOCAB = 77

  def Train(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30), teacher_forcing=True)

  def Test(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30), teacher_forcing=True, seed=99)

  def Task(self):
    p = asr_model.LasAsrModel.Params()
    p.name = "librispeech_las"
    p.vocab_size = self.VOCAB
    p.encoder.input_dim = self.NUM_BINS
    p.encoder.model_dim = self.MODEL_DIM
    p.encoder.num_layers = self.NUM_LAYERS
    p.encoder.num_heads = self.NUM_HEADS
    p.encoder.dropout_prob = 0.1
    p.decoder.rnn_cell_dim = self.MODEL_DIM
    p.decoder.beam_search.target_seq_len = 24
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=2.0,
        optimizer=opt_lib.AdamW.Params().Set(beta2=0.98, weight_decay=1e-6),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=10000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class LibrispeechLasTiny(Librispeech960GraphemeLas):
  """Smoke-test scale LAS."""

  BATCH_SIZE = 4
  NUM_BINS = 16
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  VOCAB = 30

  def Task(self):
    p = super().Task()
    p.encoder.kernel_size = 8
    p.encoder.dropout_prob = 0.0
    p.encoder.specaug.freq_mask_max_bins = 4
    p.encoder.specaug.time_mask_max_frames = 8
    p.decoder.emb_dim = 32
    p.decoder.num_rnn_layers = 1
    p.decoder.attention.hidden_dim = 32
    p.decoder.beam_search.target_seq_len = 14
    p.decoder.beam_search.num_hyps_per_beam = 4
    p.train.learner.learning_rate = 2e-3
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class Librispeech960Rnnt(base_model_params.SingleTaskModelParams):
  """Conformer transducer (the RNN-T decoder family the reference carries
  in `tasks/asr/decoder.py`; conformer-transducer recipe shapes)."""

  BATCH_SIZE = 16
  NUM_BINS = 80
  MODEL_DIM = 256
  NUM_LAYERS = 16
  NUM_HEADS = 4
  VOCAB = 77

  def Train(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30))

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    from lingvo_tpu.models.asr import rnnt
    p = rnnt.RnntAsrModel.Params()
    p.name = "librispeech_rnnt"
    p.vocab_size = self.VOCAB  # synthetic input clamps ITS vocab, not the head
    p.encoder.input_dim = self.NUM_BINS
    p.encoder.model_dim = self.MODEL_DIM
    p.encoder.num_layers = self.NUM_LAYERS
    p.encoder.num_heads = self.NUM_HEADS
    p.encoder.dropout_prob = 0.1
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=2.0,
        optimizer=opt_lib.AdamW.Params().Set(beta2=0.98, weight_decay=1e-6),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=10000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class LibrispeechRnntTiny(Librispeech960Rnnt):
  """Smoke-test scale transducer."""

  BATCH_SIZE = 4
  NUM_BINS = 16
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  VOCAB = 30

  def Task(self):
    p = super().Task()
    p.encoder.kernel_size = 8
    p.encoder.dropout_prob = 0.0
    p.encoder.specaug.freq_mask_max_bins = 4
    p.encoder.specaug.time_mask_max_frames = 8
    p.decoder.emb_dim = 16
    p.decoder.pred_dim = 32
    p.decoder.joint_dim = 32
    p.train.learner.learning_rate = 3e-3
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p
