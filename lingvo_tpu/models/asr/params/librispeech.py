"""Librispeech ASR configs (ref: lingvo/tasks/asr/params/librispeech.py
Librispeech960Grapheme:156 — grapheme LAS; here the modern Conformer-CTC
recipe at comparable scale, on synthetic input until the native pipeline
feeds real Librispeech tfrecords)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.asr import input_generator
from lingvo_tpu.models.asr import model as asr_model


@model_registry.RegisterSingleTaskModel
class Librispeech960ConformerCtc(base_model_params.SingleTaskModelParams):
  """Conformer-CTC at Librispeech-960 grapheme scale."""

  BATCH_SIZE = 16
  NUM_BINS = 80
  MODEL_DIM = 256
  NUM_LAYERS = 16
  NUM_HEADS = 4
  VOCAB = 77  # graphemes + blank (ref grapheme vocab size)

  def Train(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30))

  def Test(self):
    return input_generator.SyntheticAsrInput.Params().Set(
        batch_size=self.BATCH_SIZE, num_bins=self.NUM_BINS,
        vocab_size=min(self.VOCAB, 30), seed=99)

  def Task(self):
    p = asr_model.CtcAsrModel.Params()
    p.name = "librispeech_ctc"
    p.input_dim = self.NUM_BINS
    p.model_dim = self.MODEL_DIM
    p.num_layers = self.NUM_LAYERS
    p.num_heads = self.NUM_HEADS
    p.vocab_size = self.VOCAB
    p.dropout_prob = 0.1
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=2.0,
        optimizer=opt_lib.AdamW.Params().Set(beta2=0.98, weight_decay=1e-6),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=10000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class LibrispeechConformerCtcTiny(Librispeech960ConformerCtc):
  """Smoke-test scale."""

  BATCH_SIZE = 4
  NUM_BINS = 16
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  VOCAB = 30

  def Task(self):
    p = super().Task()
    p.kernel_size = 8
    p.dropout_prob = 0.0
    p.specaug.freq_mask_max_bins = 4
    p.specaug.time_mask_max_frames = 8
    p.train.learner.learning_rate = 2e-3
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p
