"""ASR encoder: mel frontend + SpecAugment + conv subsampling + conformer.

The shared acoustic encoder behind both the CTC and LAS tasks (ref
`lingvo/tasks/asr/encoder.py` — the reference's CNN+BiLSTM encoder family;
here the modern conformer stack, which the reference also provides via
`conformer_layer.py`, is the default and the BiLSTM variant is available
through `rnn_layers`)."""

from __future__ import annotations

import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import conformer_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import spectrum_augmenter
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.asr import frontend as frontend_lib


class AsrConformerEncoder(base_layer.BaseLayer):
  """Features/waveform -> (encoded [b, t', model_dim], paddings [b, t'])."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("frontend", frontend_lib.MelAsrFrontend.Params(),
             "Waveform frontend (unused when features are fed directly).")
    p.Define("specaug", spectrum_augmenter.SpectrumAugmenter.Params(),
             "SpecAugment.")
    p.Define("input_dim", 80, "Feature dim.")
    p.Define("model_dim", 256, "Conformer dim.")
    p.Define("num_layers", 16, "Conformer depth.")
    p.Define("num_heads", 4, "Attention heads.")
    p.Define("kernel_size", 32, "LConv kernel.")
    p.Define("dropout_prob", 0.0, "Dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild("frontend", p.frontend)
    self.CreateChild("specaug", p.specaug)
    # conv subsampling: two stride-2 convs over time (4x subsampling)
    self.CreateChild(
        "sub1",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, 1, 32), filter_stride=(2, 2),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "sub2",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, 32, 32), filter_stride=(2, 2),
            activation="RELU", batch_norm=False, has_bias=True))
    # two SAME stride-2 convs: freq -> ceil(ceil(f/2)/2)
    sub_freq = (p.input_dim + 1) // 2
    sub_freq = (sub_freq + 1) // 2
    self.CreateChild(
        "input_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=32 * sub_freq, output_dim=p.model_dim))
    blocks = []
    for _ in range(p.num_layers):
      blocks.append(conformer_layer.ConformerLayer.Params().Set(
          input_dim=p.model_dim, atten_num_heads=p.num_heads,
          kernel_size=p.kernel_size, dropout_prob=p.dropout_prob))
    self.CreateChildren("conformer", blocks)

  def FProp(self, theta, input_batch: NestedMap):
    if "features" in input_batch:
      feats = input_batch.features
      fpad = input_batch.Get("feature_paddings")
      if fpad is None:
        fpad = jnp.zeros(feats.shape[:2], jnp.float32)
    else:
      feats, fpad = self.frontend.FProp(
          self.ChildTheta(theta, "frontend"), input_batch.waveform,
          input_batch.Get("paddings"))
    feats = self.specaug.FProp(self.ChildTheta(theta, "specaug"), feats,
                               fpad)
    x = feats[..., None]                     # [b, t, f, 1]
    x, fpad = self.sub1.FProp(theta.sub1, x, fpad)
    x, fpad = self.sub2.FProp(theta.sub2, x, fpad)
    b, t = x.shape[0], x.shape[1]
    x = x.reshape(b, t, -1)
    x = self.input_proj.FProp(theta.input_proj, x)
    for i, block in enumerate(self.conformer):
      x = block.FProp(theta.conformer[i], x, fpad)
    return x, fpad
