"""RNN-T (transducer) ASR: prediction network + joint + transducer loss +
greedy decode (ref the RNN-T pieces of `lingvo/tasks/asr/decoder.py` and
the reference's transducer configs).

TPU-first: the transducer forward variable is computed with a `lax.scan`
over encoder time whose carry is one log-alpha row over label positions
(the inner emit recursion scans over U — static shapes, no host loops);
greedy decode is a bounded scan over T+U joint steps.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.asr import model as model_lib

NEG_INF = -1.0e30


def RnntLoss(logits, labels, t_lens, u_lens, blank_id: int = 0):
  """Transducer negative log-likelihood.

  logits: [B, T, U+1, V] joint outputs (U = max label length);
  labels: [B, U]; t_lens: [B] encoder lengths; u_lens: [B] label lengths.
  Returns per-sequence -log P(labels | acoustics), [B].

  Forward recursion (log domain):
    alpha[0, 0] = 0
    alpha[t, u] = logaddexp(alpha[t-1, u] + blank(t-1, u),
                            alpha[t, u-1] + emit(t, u-1))
    ll = alpha[T-1, U] + blank(T-1, U)
  """
  b, t_max, u_plus1, v = logits.shape
  u_max = u_plus1 - 1
  log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
  blank_lp = log_probs[..., blank_id]                     # [B, T, U+1]
  # emit(t, u) = log P(label_{u+1} | t, u)
  emit_lp = jnp.take_along_axis(
      log_probs[:, :, :u_max, :], labels[:, None, :, None], axis=-1
  )[..., 0]                                               # [B, T, U]
  # labels past u_len must never be emitted
  u_mask = (jnp.arange(u_max)[None] < u_lens[:, None])    # [B, U]
  emit_lp = jnp.where(u_mask[:, None, :], emit_lp, NEG_INF)

  def _EmitAlongU(alpha_from_blank, emit_row):
    """alpha'[u] = logaddexp(from_blank[u], alpha'[u-1] + emit[u-1])."""

    def _Step(prev_alpha_u, x):
      from_blank_u, emit_prev = x
      val = jnp.logaddexp(from_blank_u, prev_alpha_u + emit_prev)
      return val, val

    first = alpha_from_blank[:, 0]
    if u_max == 0:
      return first[:, None]
    # u = 1..U pairs from_blank[:, u] with emit_row[:, u-1]
    xs = (alpha_from_blank[:, 1:].swapaxes(0, 1),
          emit_row.swapaxes(0, 1))
    _, rest = jax.lax.scan(_Step, first, xs)
    return jnp.concatenate([first[:, None], rest.swapaxes(0, 1)], axis=1)

  # t = 0 row: only emits from (0, u-1)
  init_from_blank = jnp.full((b, u_plus1), NEG_INF).at[:, 0].set(0.0)
  alpha0 = _EmitAlongU(init_from_blank, emit_lp[:, 0])    # [B, U+1]

  def _TStep(alpha_prev, per_t):
    blank_prev_row, emit_row = per_t
    from_blank = alpha_prev + blank_prev_row              # [B, U+1]
    alpha = _EmitAlongU(from_blank, emit_row)
    return alpha, alpha

  if t_max > 1:
    per_t = (blank_lp[:, :-1].swapaxes(0, 1),             # blank at t-1
             emit_lp[:, 1:].swapaxes(0, 1))
    _, alphas = jax.lax.scan(_TStep, alpha0, per_t)
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, U+1]
  else:
    alphas = alpha0[None]
  alphas = alphas.swapaxes(0, 1)                          # [B, T, U+1]

  t_idx = jnp.clip(t_lens - 1, 0, t_max - 1)
  final_alpha = jnp.take_along_axis(
      alphas, t_idx[:, None, None].repeat(u_plus1, 2), axis=1)[:, 0]
  final_alpha = jnp.take_along_axis(final_alpha, u_lens[:, None], 1)[:, 0]
  final_blank = jnp.take_along_axis(
      blank_lp, t_idx[:, None, None].repeat(u_plus1, 2), axis=1)[:, 0]
  final_blank = jnp.take_along_axis(final_blank, u_lens[:, None], 1)[:, 0]
  return -(final_alpha + final_blank)


class RnntDecoder(base_layer.BaseLayer):
  """Prediction network + joint (ref RNN-T decoder pieces)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 30, "Vocab incl. blank at 0.")
    p.Define("emb_dim", 64, "Label embedding dim.")
    p.Define("pred_dim", 128, "Prediction LSTM dim.")
    p.Define("joint_dim", 128, "Joint hidden dim.")
    p.Define("source_dim", 256, "Encoder output dim.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "emb", layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.emb_dim))
    self.CreateChild(
        "pred_cell", rnn_cell.LSTMCellSimple.Params().Set(
            num_input_nodes=p.emb_dim, num_output_nodes=p.pred_dim))
    self.CreateChild(
        "enc_proj", layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.source_dim, output_dim=p.joint_dim, has_bias=False))
    self.CreateChild(
        "pred_proj", layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.pred_dim, output_dim=p.joint_dim))
    self.CreateChild(
        "joint_out", layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.joint_dim, output_dim=p.vocab_size))

  def PredictNet(self, theta, labels):
    """labels [B, U] -> prediction activations [B, U+1, pred_dim]
    (position 0 = the 'blank so far' start state)."""
    b, u = labels.shape
    emb = self.emb.EmbLookup(self.ChildTheta(theta, "emb"), labels)

    def _Step(state, x_t):
      new_state = self.pred_cell.FProp(theta.pred_cell, state, x_t)
      return new_state, self.pred_cell.GetOutput(new_state)

    state0 = self.pred_cell.InitState(b)
    zero = jnp.zeros((b, self.p.pred_dim), emb.dtype)
    _, outs = jax.lax.scan(_Step, state0, emb.swapaxes(0, 1))
    return jnp.concatenate([zero[:, None], outs.swapaxes(0, 1)], axis=1)

  def Joint(self, theta, enc, pred):
    """enc [B, T, D], pred [B, U+1, P] -> logits [B, T, U+1, V]."""
    e = self.enc_proj.FProp(theta.enc_proj, enc)          # [B, T, J]
    g = self.pred_proj.FProp(theta.pred_proj, pred)       # [B, U+1, J]
    h = jnp.tanh(e[:, :, None, :] + g[:, None, :, :])
    return self.joint_out.FProp(theta.joint_out, h)

  def GreedyDecode(self, theta, enc, enc_paddings, max_symbols: int):
    """Frame-synchronous greedy transducer decode: at each joint step emit
    the argmax; blank advances time, a label advances the prediction net
    (bounded at T + max_symbols steps)."""
    p = self.p
    b, t_max, _ = enc.shape
    e = self.enc_proj.FProp(theta.enc_proj, enc)          # [B, T, J]
    t_lens = jnp.sum(1.0 - enc_paddings, axis=1).astype(jnp.int32)

    def _Step(carry, _):
      t_idx, pred_state, pred_out, hyp, hyp_len = carry
      e_t = jnp.take_along_axis(
          e, jnp.clip(t_idx, 0, t_max - 1)[:, None, None].repeat(
              e.shape[-1], 2), axis=1)[:, 0]
      g = self.pred_proj.FProp(theta.pred_proj, pred_out)
      logits = self.joint_out.FProp(theta.joint_out, jnp.tanh(e_t + g))
      sym = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B]
      done = t_idx >= t_lens
      is_blank = (sym == 0) | done
      # on a label: extend hyp + step the prediction net
      emb = self.emb.EmbLookup(self.ChildTheta(theta, "emb"),
                               sym[:, None])[:, 0]
      new_state = self.pred_cell.FProp(theta.pred_cell, pred_state, emb)

      def _Sel(new, old):
        k = is_blank.reshape((-1,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return old * k + new * (1 - k)

      pred_state = jax.tree_util.tree_map(_Sel, new_state, pred_state)
      pred_out = _Sel(self.pred_cell.GetOutput(new_state), pred_out)
      write = (~is_blank) & (hyp_len < hyp.shape[1])
      hyp = jnp.where(
          (jnp.arange(hyp.shape[1])[None] == hyp_len[:, None])
          & write[:, None], sym[:, None], hyp)
      hyp_len = hyp_len + write.astype(jnp.int32)
      t_idx = t_idx + is_blank.astype(jnp.int32)
      return (t_idx, pred_state, pred_out, hyp, hyp_len), ()

    hyp0 = jnp.zeros((b, max_symbols), jnp.int32)
    carry = (jnp.zeros((b,), jnp.int32), self.pred_cell.InitState(b),
             jnp.zeros((b, p.pred_dim), enc.dtype), hyp0,
             jnp.zeros((b,), jnp.int32))
    (t_idx, _, _, hyp, hyp_len), _ = jax.lax.scan(
        _Step, carry, None, length=t_max + max_symbols)
    return hyp, hyp_len


  def BeamDecode(self, theta, enc, enc_paddings, max_symbols: int,
                 beam_size: int = 4):
    """Frame-asynchronous K-hypothesis transducer beam search (VERDICT r2
    Next #5; ref ASR beam decoding work — the reference ships greedy plus
    beam variants in `tasks/asr/decoder.py`).

    Each hypothesis carries its own time cursor: a blank consumes a frame,
    a label steps the prediction net; every global step expands all K
    hypotheses over the vocab and keeps the top K by accumulated log-prob
    (no prefix merging — ALSD-style). With beam_size=1 this reduces
    exactly to GreedyDecode. Returns (hyp [B, max_symbols], hyp_len [B])
    for the best-scoring hypothesis.
    """
    p = self.p
    b, t_max, _ = enc.shape
    k = beam_size
    bk = b * k
    neg_inf = -1.0e9
    e = self.enc_proj.FProp(theta.enc_proj, enc)          # [B, T, J]
    e_tiled = jnp.repeat(e, k, axis=0)                    # [B*K, T, J]
    t_lens = jnp.repeat(
        jnp.sum(1.0 - enc_paddings, axis=1).astype(jnp.int32), k)

    def _GatherParents(x, parent):
      shaped = x.reshape((b, k) + x.shape[1:])
      idx = parent.reshape((b, k) + (1,) * (x.ndim - 1)).astype(jnp.int32)
      return jnp.take_along_axis(shaped, idx, axis=1).reshape(x.shape)

    def _Step(carry, _):
      t_idx, pred_state, pred_out, hyp, hyp_len, score = carry
      e_t = jnp.take_along_axis(
          e_tiled, jnp.clip(t_idx, 0, t_max - 1)[:, None, None].repeat(
              e_tiled.shape[-1], 2), axis=1)[:, 0]        # [B*K, J]
      g = self.pred_proj.FProp(theta.pred_proj, pred_out)
      logits = self.joint_out.FProp(theta.joint_out, jnp.tanh(e_t + g))
      log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
      vocab = log_probs.shape[-1]
      # exhausted hyps are frozen: blank continuation at zero cost
      done = t_idx >= t_lens
      frozen = jnp.full((vocab,), neg_inf).at[0].set(0.0)
      log_probs = jnp.where(done[:, None], frozen[None, :], log_probs)

      total = (score[:, None] + log_probs).reshape(b, k * vocab)
      new_score, flat = jax.lax.top_k(total, k)           # [B, K]
      parent = flat // vocab
      token = (flat % vocab).astype(jnp.int32).reshape(bk)
      new_score = new_score.reshape(bk)

      t_idx = _GatherParents(t_idx, parent)
      pred_state = jax.tree_util.tree_map(
          lambda x: _GatherParents(x, parent), pred_state)
      pred_out = _GatherParents(pred_out, parent)
      hyp = _GatherParents(hyp, parent)
      hyp_len = _GatherParents(hyp_len, parent)

      is_blank = token == 0
      emb = self.emb.EmbLookup(self.ChildTheta(theta, "emb"),
                               token[:, None])[:, 0]
      stepped = self.pred_cell.FProp(theta.pred_cell, pred_state, emb)

      def _Sel(new, old):
        m = is_blank.reshape((-1,) + (1,) * (new.ndim - 1)).astype(new.dtype)
        return old * m + new * (1 - m)

      pred_state = jax.tree_util.tree_map(_Sel, stepped, pred_state)
      pred_out = _Sel(self.pred_cell.GetOutput(stepped), pred_out)
      write = (~is_blank) & (hyp_len < hyp.shape[1])
      hyp = jnp.where(
          (jnp.arange(hyp.shape[1])[None] == hyp_len[:, None])
          & write[:, None], token[:, None], hyp)
      hyp_len = hyp_len + write.astype(jnp.int32)
      t_idx = t_idx + is_blank.astype(jnp.int32)
      return (t_idx, pred_state, pred_out, hyp, hyp_len, new_score), ()

    # beam 0 live, others -inf so all start from one empty hypothesis
    score0 = jnp.tile(jnp.asarray([0.0] + [neg_inf] * (k - 1)), (b,))
    carry = (jnp.zeros((bk,), jnp.int32), self.pred_cell.InitState(bk),
             jnp.zeros((bk, p.pred_dim), enc.dtype),
             jnp.zeros((bk, max_symbols), jnp.int32),
             jnp.zeros((bk,), jnp.int32), score0)
    (t_idx, _, _, hyp, hyp_len, score), _ = jax.lax.scan(
        _Step, carry, None, length=t_max + max_symbols)
    best = jnp.argmax(score.reshape(b, k), axis=1)        # [B]
    hyp = jnp.take_along_axis(
        hyp.reshape(b, k, max_symbols), best[:, None, None], axis=1)[:, 0]
    hyp_len = jnp.take_along_axis(
        hyp_len.reshape(b, k), best[:, None], axis=1)[:, 0]
    return hyp, hyp_len


class RnntAsrModel(model_lib._AsrTaskBase):
  """Conformer encoder + RNN-T decoder (shares _AsrTaskBase's encoder
  wiring and WER decode metrics).

  Batch: features/feature_paddings (or waveform), tgt.ids [B, U] (content
  ids >= 1, no sos/eos framing) + tgt.paddings — the CTC label layout.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("decoder", RnntDecoder.Params(), "RNN-T decoder.")
    p.Define("max_decode_symbols", 32, "Decode label budget.")
    p.Define("decode_beam_size", 1,
             "1 = frame-synchronous greedy; >1 = transducer beam search "
             "(RnntDecoder.BeamDecode).")
    return p

  def __init__(self, params):
    p = params
    p.decoder.vocab_size = p.vocab_size
    p.decoder.source_dim = p.encoder.model_dim
    super().__init__(p)
    self.CreateChild("decoder", p.decoder)

  def ComputePredictions(self, theta, input_batch):
    enc, enc_pad = self._Encode(theta, input_batch)
    dec_theta = self.ChildTheta(theta, "decoder")
    pred = self.decoder.PredictNet(dec_theta, input_batch.tgt.ids)
    logits = self.decoder.Joint(dec_theta, enc, pred)
    return NestedMap(logits=logits, enc_paddings=enc_pad)

  def ComputeLoss(self, theta, predictions, input_batch):
    t_lens = jnp.sum(1.0 - predictions.enc_paddings, 1).astype(jnp.int32)
    u_lens = jnp.sum(1.0 - input_batch.tgt.paddings, 1).astype(jnp.int32)
    nll = RnntLoss(predictions.logits, input_batch.tgt.ids, t_lens, u_lens)
    per_label = nll / jnp.maximum(u_lens.astype(jnp.float32), 1.0)
    b = float(nll.shape[0])
    return NestedMap(loss=(jnp.mean(per_label), b)), NestedMap(nll=nll)

  def Decode(self, theta, input_batch):
    enc, enc_pad = self._Encode(theta, input_batch)
    if self.p.decode_beam_size > 1:
      hyp, hyp_len = self.decoder.BeamDecode(
          self.ChildTheta(theta, "decoder"), enc, enc_pad,
          self.p.max_decode_symbols, beam_size=self.p.decode_beam_size)
    else:
      hyp, hyp_len = self.decoder.GreedyDecode(
          self.ChildTheta(theta, "decoder"), enc, enc_pad,
          self.p.max_decode_symbols)
    return NestedMap(hyp_ids=hyp, hyp_lens=hyp_len,
                     target_ids=input_batch.tgt.ids,
                     target_paddings=input_batch.tgt.paddings)

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    hyps = np.asarray(decode_out.hyp_ids)
    lens = np.asarray(decode_out.hyp_lens)
    labels = np.asarray(decode_out.target_ids)
    lpads = np.asarray(decode_out.target_paddings)
    for i in range(hyps.shape[0]):
      hyp = [int(x) for x in hyps[i, :int(lens[i])]]
      ref_len = int((1.0 - lpads[i]).sum())
      ref = [int(x) for x in labels[i, :ref_len]]
      decoder_metrics["wer"].Update(ref, hyp)
