"""ASR decode metrics: WER via Levenshtein distance.

Ref: lingvo/tasks/asr/decoder_metrics.py + levenshtein_distance.py.
"""

from __future__ import annotations

from lingvo_tpu.core import metrics as metrics_lib


def LevenshteinDistance(ref: list, hyp: list) -> int:
  """Edit distance between token lists (ref levenshtein_distance.py)."""
  m, n = len(ref), len(hyp)
  if m == 0:
    return n
  if n == 0:
    return m
  prev = list(range(n + 1))
  for i in range(1, m + 1):
    cur = [i] + [0] * n
    for j in range(1, n + 1):
      cost = 0 if ref[i - 1] == hyp[j - 1] else 1
      cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
    prev = cur
  return prev[n]


class WerMetric(metrics_lib.BaseMetric):
  """Word (token) error rate accumulator."""

  def __init__(self):
    self._errors = 0
    self._ref_tokens = 0
    self._num_utts = 0

  def Update(self, ref_tokens: list, hyp_tokens: list):
    self._errors += LevenshteinDistance(ref_tokens, hyp_tokens)
    self._ref_tokens += len(ref_tokens)
    self._num_utts += 1

  @property
  def value(self) -> float:
    return self._errors / max(self._ref_tokens, 1)

  @property
  def num_utterances(self) -> int:
    return self._num_utts
