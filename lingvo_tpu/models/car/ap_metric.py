"""Rotated-box detection AP (ref `lingvo/tasks/car/ap_metric.py` +
`geometry.py` rotated-IoU): BEV IoU via convex polygon clipping
(Sutherland–Hodgman), greedy score-ordered matching, all-point
average precision.

Host-side numpy (decode postprocess), like the reference's metric code.
"""

from __future__ import annotations

import numpy as np


def BoxCorners(box: np.ndarray) -> np.ndarray:
  """[cx, cy, l, w, theta] -> [4, 2] corners (counter-clockwise)."""
  cx, cy, l, w, theta = box[:5] if len(box) == 5 else (
      box[0], box[1], box[3], box[4], box[6])
  dx, dy = l / 2.0, w / 2.0
  corners = np.array([[dx, dy], [-dx, dy], [-dx, -dy], [dx, -dy]])
  c, s = np.cos(theta), np.sin(theta)
  rot = np.array([[c, -s], [s, c]])
  return corners @ rot.T + np.array([cx, cy])


def _PolygonArea(poly: np.ndarray) -> float:
  if len(poly) < 3:
    return 0.0
  x, y = poly[:, 0], poly[:, 1]
  return 0.5 * abs(float(np.dot(x, np.roll(y, -1)) -
                         np.dot(y, np.roll(x, -1))))


def _ClipPolygon(poly, a, b):
  """Clips polygon by the half-plane left of edge a->b (Sutherland–Hodgman)."""
  out = []
  n = len(poly)
  for i in range(n):
    cur, nxt = poly[i], poly[(i + 1) % n]
    cur_in = _Cross(a, b, cur) >= 0
    nxt_in = _Cross(a, b, nxt) >= 0
    if cur_in:
      out.append(cur)
      if not nxt_in:
        out.append(_Intersect(a, b, cur, nxt))
    elif nxt_in:
      out.append(_Intersect(a, b, cur, nxt))
  return np.asarray(out) if out else np.zeros((0, 2))


def _Cross(a, b, p):
  return (b[0] - a[0]) * (p[1] - a[1]) - (b[1] - a[1]) * (p[0] - a[0])


def _Intersect(a, b, p, q):
  d1 = _Cross(a, b, p)
  d2 = _Cross(a, b, q)
  t = d1 / (d1 - d2) if d1 != d2 else 0.0
  return p + t * (q - p)


def RotatedIou(box1: np.ndarray, box2: np.ndarray) -> float:
  """BEV IoU of two rotated boxes [cx, cy, l, w, theta] (or 7-dof)."""
  p1 = BoxCorners(np.asarray(box1, np.float64))
  p2 = BoxCorners(np.asarray(box2, np.float64))
  inter = p1
  for i in range(4):
    if len(inter) == 0:
      break
    inter = _ClipPolygon(inter, p2[i], p2[(i + 1) % 4])
  ai = _PolygonArea(inter)
  a1, a2 = _PolygonArea(p1), _PolygonArea(p2)
  union = a1 + a2 - ai
  return ai / union if union > 0 else 0.0


def AveragePrecision(matches: list[tuple[float, bool]],
                     num_gt: int) -> float:
  """All-point AP from (score, is_true_positive) detections.

  matches: every detection with its score and whether it matched a gt.
  """
  if num_gt == 0:
    return 0.0
  if not matches:
    return 0.0
  matches = sorted(matches, key=lambda m: -m[0])
  tp = np.cumsum([1.0 if m[1] else 0.0 for m in matches])
  fp = np.cumsum([0.0 if m[1] else 1.0 for m in matches])
  recall = tp / num_gt
  precision = tp / np.maximum(tp + fp, 1e-9)
  # all-point interpolation: precision envelope integrated over recall
  prec_env = np.maximum.accumulate(precision[::-1])[::-1]
  ap = 0.0
  prev_r = 0.0
  for r, p in zip(recall, prec_env):
    ap += (r - prev_r) * p
    prev_r = r
  return float(ap)


class ApMetric:
  """Accumulates rotated-IoU-matched detections across batches.

  Class-aware when Update is given per-box class labels (ref
  `ap_metric.py` computes AP per metadata class then averages): detections
  only match ground truth of the same class, and `value` is the mean AP
  over classes that have ground truth. Without labels everything lands in
  one class bucket (class-agnostic AP)."""

  def __init__(self, iou_threshold: float = 0.5):
    self._iou = iou_threshold
    self._matches: dict[int, list[tuple[float, bool]]] = {}
    self._num_gt: dict[int, int] = {}

  def Update(self, pred_boxes: np.ndarray, pred_scores: np.ndarray,
             gt_boxes: np.ndarray, pred_classes: np.ndarray = None,
             gt_classes: np.ndarray = None):
    """pred_boxes [P, 5+], pred_scores [P], gt_boxes [G, 5+] (one scene);
    greedy score-ordered matching per class, one detection per gt."""
    if pred_classes is None:
      pred_classes = np.zeros((len(pred_boxes),), np.int32)
    if gt_classes is None:
      gt_classes = np.zeros((len(gt_boxes),), np.int32)
    for c in np.unique(gt_classes):
      self._num_gt[int(c)] = self._num_gt.get(int(c), 0) + int(
          np.sum(gt_classes == c))
    order = np.argsort(-np.asarray(pred_scores))
    taken = set()
    for i in order:
      cls = int(pred_classes[i])
      best_iou, best_j = 0.0, -1
      for j in range(len(gt_boxes)):
        if j in taken or int(gt_classes[j]) != cls:
          continue
        iou = RotatedIou(pred_boxes[i], gt_boxes[j])
        if iou > best_iou:
          best_iou, best_j = iou, j
      matched = best_iou >= self._iou and best_j >= 0
      if matched:
        taken.add(best_j)
      self._matches.setdefault(cls, []).append(
          (float(pred_scores[i]), matched))

  @property
  def value(self) -> float:
    """Mean AP over classes with ground truth."""
    aps = [AveragePrecision(self._matches.get(c, []), n)
           for c, n in self._num_gt.items() if n > 0]
    return float(np.mean(aps)) if aps else 0.0

  @property
  def num_ground_truth(self) -> int:
    return sum(self._num_gt.values())

  @property
  def detections(self) -> list[tuple[float, bool]]:
    """All accumulated (score, matched) pairs across classes — the stream
    calibration metrics consume."""
    out = []
    for matches in self._matches.values():
      out.extend(matches)
    return out
