"""3D detection data augmentation: host-side numpy scene transforms.

Re-designs the capability of the reference's augmentation preprocessors
(`lingvo/tasks/car/input_preprocessors.py`: RandomWorldRotationAboutZAxis
:1754, WorldScaling:2088, RandomDropLaserPoints:2156, RandomFlipY:2204,
GlobalTranslateNoise:2278, RandomBBoxTransform:2361, GroundTruthAugmentor
:2708, FrustumDropout:3093, RandomApplyPreprocessor:3298,
RandomChoicePreprocessor:3445, Sequence:3527) for the TPU-native input
design: the reference runs these as TF graph ops inside the input pipeline;
here scenes are plain numpy on the host (points [N,F] with xyz in columns
0:3, boxes [M,7] (x,y,z,dx,dy,dz,phi), classes [M]) transformed BEFORE the
fixed-shape view assembly, so the device program never sees dynamic shapes.

Composable `Augmentor` objects with `Apply(scene, rng) -> scene`; build a
pipeline from Params via `BuildPipeline`, hook it on the KITTI/Waymo
generators with `p.augmentors`. All randomness flows through one
numpy Generator seeded per record for reproducibility.

Scene contract: NestedMap(points [N,F>=3] f32, boxes [M,7] f32,
classes [M] i32); augmentors must keep dtypes and the [*,7] box layout.
"""

from __future__ import annotations

import math

import numpy as np

from lingvo_tpu.core import hyperparams
from lingvo_tpu.core.nested_map import NestedMap


# ---------------------------------------------------------------------------
# geometry helpers (numpy; device-side twins live in detection_3d.py)
# ---------------------------------------------------------------------------


def RotZ(phi: float) -> np.ndarray:
  c, s = math.cos(phi), math.sin(phi)
  return np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]], np.float32)


def PointsInBoxes(points: np.ndarray, boxes: np.ndarray) -> np.ndarray:
  """points [N,>=3], boxes [M,7] -> bool [N,M] membership.

  A point is in a box when its box-frame coordinates fall inside the
  half-dimensions (ref geometry.IsWithinBBox3D semantics).
  """
  n = points.shape[0]
  m = boxes.shape[0]
  if n == 0 or m == 0:
    return np.zeros((n, m), bool)
  xyz = points[:, None, :3] - boxes[None, :, :3]              # [N,M,3]
  c = np.cos(-boxes[:, 6])
  s = np.sin(-boxes[:, 6])
  x = xyz[..., 0] * c[None] - xyz[..., 1] * s[None]
  y = xyz[..., 0] * s[None] + xyz[..., 1] * c[None]
  z = xyz[..., 2]
  half = boxes[:, 3:6] / 2.0
  return ((np.abs(x) <= half[None, :, 0]) &
          (np.abs(y) <= half[None, :, 1]) &
          (np.abs(z) <= half[None, :, 2]))


def _BevCorners(boxes: np.ndarray) -> np.ndarray:
  """[M,7] -> [M,4,2] rotated BEV rectangle corners."""
  m = boxes.shape[0]
  dx, dy = boxes[:, 3] / 2.0, boxes[:, 4] / 2.0
  base = np.stack([np.stack([dx, dy], -1), np.stack([-dx, dy], -1),
                   np.stack([-dx, -dy], -1), np.stack([dx, -dy], -1)],
                  axis=1)                                      # [M,4,2]
  c, s = np.cos(boxes[:, 6]), np.sin(boxes[:, 6])
  rot = np.stack([np.stack([c, -s], -1), np.stack([s, c], -1)], axis=1)
  return np.einsum("mij,mkj->mki", rot, base) + boxes[:, None, :2]


def BevBoxOverlap(boxes_a: np.ndarray, boxes_b: np.ndarray) -> np.ndarray:
  """Conservative BEV overlap test [A,B] via separating-axis on the two
  rectangles' axes — exact for rectangles (used for collision REJECTION, so
  exactness beats IoU magnitude; ref GroundTruthAugmentor filters samples
  by bboxes3d overlap)."""
  a, b = boxes_a.shape[0], boxes_b.shape[0]
  if a == 0 or b == 0:
    return np.zeros((a, b), bool)
  ca = _BevCorners(boxes_a)                                    # [A,4,2]
  cb = _BevCorners(boxes_b)                                    # [B,4,2]
  overlap = np.ones((a, b), bool)
  for boxes, from_a in ((boxes_a, True), (boxes_b, False)):
    phis = boxes[:, 6]
    axes = np.stack(
        [np.stack([np.cos(phis), np.sin(phis)], -1),
         np.stack([-np.sin(phis), np.cos(phis)], -1)], axis=1)  # [M,2,2]
    pa = np.einsum("akd,mjd->amjk", ca, axes)  # [A,M,2 axes,4 corners]
    pb = np.einsum("bkd,mjd->bmjk", cb, axes)
    if from_a:
      ia = np.arange(a)
      a_lo, a_hi = pa[ia, ia].min(-1), pa[ia, ia].max(-1)       # [A,2]
      b_lo, b_hi = pb.min(-1), pb.max(-1)                       # [B,A,2]
      sep = (a_hi[None] < b_lo) | (b_hi < a_lo[None])           # [B,A,2]
      overlap &= ~sep.any(-1).T
    else:
      ib = np.arange(b)
      b_lo, b_hi = pb[ib, ib].min(-1), pb[ib, ib].max(-1)       # [B,2]
      a_lo, a_hi = pa.min(-1), pa.max(-1)                       # [A,B,2]
      sep = (b_hi[None] < a_lo) | (a_hi < b_lo[None])           # [A,B,2]
      overlap &= ~sep.any(-1)
  return overlap


# ---------------------------------------------------------------------------
# augmentor base + pipeline
# ---------------------------------------------------------------------------


def _With(scene: NestedMap, **updates) -> NestedMap:
  out = scene.Copy() if hasattr(scene, "Copy") else NestedMap(dict(scene))
  for k, v in updates.items():
    out[k] = v
  return out


def _KeepBoxes(scene: NestedMap, keep: np.ndarray) -> NestedMap:
  """Applies a per-box keep mask to boxes/classes (+difficulty and any
  `box_extras` per-box arrays if carried)."""
  updates = dict(boxes=scene.boxes[keep], classes=scene.classes[keep])
  if scene.Get("difficulty") is not None:
    updates["difficulty"] = scene.difficulty[keep]
  if scene.Get("box_extras") is not None:
    updates["box_extras"] = {k: v[keep]
                             for k, v in scene.box_extras.items()}
  return _With(scene, **updates)


class Augmentor:
  """One scene transform. Subclasses override _Apply."""

  @classmethod
  def Params(cls):
    p = hyperparams.InstantiableParams(cls)
    p.Define("name", cls.__name__, "Augmentor name.")
    return p

  def __init__(self, params):
    self.p = params.Copy()
    self.p.Freeze()

  def Apply(self, scene: NestedMap, rng: np.random.Generator) -> NestedMap:
    out = self._Apply(scene, rng)
    assert out.points.dtype == np.float32 and out.boxes.dtype == np.float32
    return out

  def _Apply(self, scene, rng):
    raise NotImplementedError


def BuildPipeline(augmentor_params: list) -> list:
  return [p.Instantiate() for p in augmentor_params]


def ApplyPipeline(augmentors: list, scene: NestedMap, seed: int) -> NestedMap:
  rng = np.random.default_rng(seed)
  for a in augmentors:
    scene = a.Apply(scene, rng)
  return scene


def MakeScene(points, boxes, classes) -> NestedMap:
  return NestedMap(
      points=np.asarray(points, np.float32).reshape(-1, 4)
      if np.asarray(points).ndim != 2 else np.asarray(points, np.float32),
      boxes=np.asarray(boxes, np.float32).reshape(-1, 7),
      classes=np.asarray(classes, np.int32).reshape(-1))


# ---------------------------------------------------------------------------
# world-level transforms
# ---------------------------------------------------------------------------


class RandomWorldRotationAboutZAxis(Augmentor):
  """Rotate the whole scene about +z by U(-max, +max) (ref :1754)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("max_rotation", math.pi / 4.0,
             "Rotation sampled from U(-max_rotation, max_rotation).")
    return p

  def _Apply(self, scene, rng):
    phi = float(rng.uniform(-self.p.max_rotation, self.p.max_rotation))
    rot = RotZ(phi)
    pts = scene.points.copy()
    pts[:, :3] = pts[:, :3] @ rot.T
    boxes = scene.boxes.copy()
    if boxes.size:
      boxes[:, :3] = boxes[:, :3] @ rot.T
      boxes[:, 6] = boxes[:, 6] + phi
    return _With(scene, points=pts, boxes=boxes)


class RandomFlipY(Augmentor):
  """Mirror the scene across the x axis (y -> -y) with probability
  flip_probability (ref :2204; phi -> -phi under the mirror)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("flip_probability", 0.5, "P(flip).")
    return p

  def _Apply(self, scene, rng):
    if rng.uniform() >= self.p.flip_probability:
      return scene
    pts = scene.points.copy()
    pts[:, 1] = -pts[:, 1]
    boxes = scene.boxes.copy()
    if boxes.size:
      boxes[:, 1] = -boxes[:, 1]
      boxes[:, 6] = -boxes[:, 6]
    return _With(scene, points=pts, boxes=boxes)


class WorldScaling(Augmentor):
  """Scale the world uniformly by U(min, max) (ref :2088). Dimensions and
  positions scale; angles don't."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("scaling", (0.95, 1.05), "(min, max) uniform scale range.")
    return p

  def _Apply(self, scene, rng):
    lo, hi = self.p.scaling
    s = float(rng.uniform(lo, hi))
    pts = scene.points.copy()
    pts[:, :3] *= s
    boxes = scene.boxes.copy()
    if boxes.size:
      boxes[:, :6] *= s
    return _With(scene, points=pts, boxes=boxes)


class GlobalTranslateNoise(Augmentor):
  """Translate the whole scene by N(0, std) per axis (ref :2278)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("noise_std", (0.2, 0.2, 0.2), "(x, y, z) translation stds.")
    return p

  def _Apply(self, scene, rng):
    t = rng.normal(0.0, self.p.noise_std).astype(np.float32)
    pts = scene.points.copy()
    pts[:, :3] += t
    boxes = scene.boxes.copy()
    if boxes.size:
      boxes[:, :3] += t
    return _With(scene, points=pts, boxes=boxes)


# ---------------------------------------------------------------------------
# point-level transforms
# ---------------------------------------------------------------------------


class RandomDropLaserPoints(Augmentor):
  """Keep each laser point with probability keep_prob (ref :2156)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("keep_prob", 0.95, "Per-point keep probability.")
    return p

  def _Apply(self, scene, rng):
    keep = rng.uniform(size=scene.points.shape[0]) < self.p.keep_prob
    return _With(scene, points=scene.points[keep])


class FrustumDropout(Augmentor):
  """Drop (or noise) points inside a random view frustum (ref :3093).

  Picks a random KEPT point, converts points to (theta, phi) spherical
  angles from the sensor origin, and drops points whose angles fall within
  (theta_width, phi_width) of the picked point's — with `keep_prob` giving
  each in-frustum point a survival chance, and distance-gating via
  `drop_type`: 'union' drops all in-frustum points, 'far' only those
  farther than the picked point.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("theta_width", 0.03, "Azimuth half... full width (radians).")
    p.Define("phi_width", 0.0, "Elevation width (radians); 0 = all.")
    p.Define("keep_prob", 0.0, "In-frustum survival probability.")
    p.Define("drop_type", "union", "'union' | 'far'.")
    return p

  def _Apply(self, scene, rng):
    pts = scene.points
    n = pts.shape[0]
    if n == 0:
      return scene
    xyz = pts[:, :3]
    r_xy = np.hypot(xyz[:, 0], xyz[:, 1])
    theta = np.arctan2(xyz[:, 1], xyz[:, 0])
    phi = np.arctan2(xyz[:, 2], np.maximum(r_xy, 1e-6))
    i = int(rng.integers(n))
    d_theta = np.abs(np.angle(np.exp(1j * (theta - theta[i]))))
    in_frustum = d_theta <= self.p.theta_width / 2.0
    if self.p.phi_width > 0:
      in_frustum &= np.abs(phi - phi[i]) <= self.p.phi_width / 2.0
    if self.p.drop_type == "far":
      dist = np.linalg.norm(xyz, axis=-1)
      in_frustum &= dist >= dist[i]
    survive = rng.uniform(size=n) < self.p.keep_prob
    keep = ~in_frustum | survive
    return _With(scene, points=scene.points[keep])


# ---------------------------------------------------------------------------
# box-level transforms
# ---------------------------------------------------------------------------


class RandomBBoxTransform(Augmentor):
  """Independently jitter each gt box (rotation about its center +
  translation noise), carrying the points inside it along and rejecting
  moves that collide with another box (ref :2361).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("max_rotation", math.pi / 10.0, "Per-box yaw jitter bound.")
    p.Define("noise_std", (0.5, 0.5, 0.0), "Per-box translation stds.")
    return p

  def _Apply(self, scene, rng):
    boxes = scene.boxes.copy()
    pts = scene.points.copy()
    m = boxes.shape[0]
    if m == 0:
      return scene
    membership = PointsInBoxes(pts, boxes)                     # [N,M]
    for j in range(m):
      phi = float(rng.uniform(-self.p.max_rotation, self.p.max_rotation))
      t = rng.normal(0.0, self.p.noise_std).astype(np.float32)
      cand = boxes[j].copy()
      cand[:3] += t
      cand[6] += phi
      others = np.delete(boxes, j, axis=0)
      if others.size and BevBoxOverlap(cand[None], others).any():
        continue  # collision: keep the original placement
      inside = membership[:, j]
      if inside.any():
        rel = pts[inside, :3] - boxes[j, :3]
        pts[inside, :3] = rel @ RotZ(phi).T + boxes[j, :3] + t
      boxes[j] = cand
    return _With(scene, points=pts, boxes=boxes)


class GroundTruthAugmentor(Augmentor):
  """Paste ground-truth objects sampled from a database into the scene
  (ref :2708): each db entry is a (box, class, points-in-box) triple
  harvested from other scenes; sampled entries are added unless they
  overlap an existing (or already-pasted) box in BEV.

  db: list of dicts {"box": [7], "class": int, "points": [K,F]} — build one
  with `BuildGroundTruthDb` over the training scenes.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("db", [], "Ground-truth database (list of entry dicts).")
    p.Define("num_to_add", 4, "Target objects pasted per scene.")
    p.Define("filter_min_points", 1,
             "Skip db entries with fewer interior points.")
    p.Define("allowed_classes", (), "If set, only paste these class ids.")
    return p

  def _Apply(self, scene, rng):
    p = self.p
    db = [e for e in p.db
          if len(e["points"]) >= p.filter_min_points
          and (not p.allowed_classes or e["class"] in p.allowed_classes)]
    if not db:
      return scene
    pts = scene.points
    boxes = scene.boxes
    classes = scene.classes
    order = rng.permutation(len(db))
    added = 0
    for idx in order:
      if added >= p.num_to_add:
        break
      entry = db[int(idx)]
      cand = np.asarray(entry["box"], np.float32)
      if boxes.size and BevBoxOverlap(cand[None], boxes).any():
        continue
      new_pts = np.asarray(entry["points"], np.float32)
      if new_pts.shape[1] < pts.shape[1]:   # pad missing features with 0
        pad = np.zeros((new_pts.shape[0], pts.shape[1] - new_pts.shape[1]),
                       np.float32)
        new_pts = np.concatenate([new_pts, pad], axis=1)
      new_pts = new_pts[:, :pts.shape[1]]
      # carve out any scene points inside the pasted box (the real object
      # occludes whatever background was there)
      if pts.size:
        inside = PointsInBoxes(pts, cand[None])[:, 0]
        pts = pts[~inside]
      pts = np.concatenate([pts, new_pts], axis=0)
      boxes = np.concatenate([boxes, cand[None]], axis=0)
      classes = np.concatenate(
          [classes, np.asarray([entry["class"]], np.int32)])
      if scene.Get("difficulty") is not None:
        scene = _With(scene, difficulty=np.concatenate(
            [scene.difficulty,
             np.asarray([entry.get("difficulty", -1)], np.int32)]))
      if scene.Get("box_extras") is not None:
        # pasted entries have no per-box extras: pad with zeros
        scene = _With(scene, box_extras={
            k: np.concatenate([v, np.zeros((1,) + v.shape[1:], v.dtype)])
            for k, v in scene.box_extras.items()})
      added += 1
    return _With(scene, points=pts.astype(np.float32),
                 boxes=boxes.astype(np.float32), classes=classes)


def BuildGroundTruthDb(scenes, min_points: int = 1) -> list:
  """Harvest (box, class, interior points) entries from scene dicts/NestedMaps
  (the GroundTruthAugmentor's database builder; the reference ships a
  separate tool — `create_kitti_crop_dataset` — that writes the same thing
  to disk)."""
  db = []
  for sc in scenes:
    pts = np.asarray(sc["points"] if isinstance(sc, dict) else sc.points,
                     np.float32)
    boxes = np.asarray(sc["boxes"] if isinstance(sc, dict) else sc.boxes,
                       np.float32).reshape(-1, 7)
    classes = np.asarray(
        sc["classes"] if isinstance(sc, dict) else sc.classes, np.int32)
    if not boxes.size:
      continue
    member = PointsInBoxes(pts, boxes)
    for j in range(boxes.shape[0]):
      interior = pts[member[:, j]]
      if interior.shape[0] >= min_points:
        db.append({"box": boxes[j].tolist(), "class": int(classes[j]),
                   "points": interior})
  return db


# ---------------------------------------------------------------------------
# filters
# ---------------------------------------------------------------------------


class DropPointsOutOfRange(Augmentor):
  """Keep only points inside an axis-aligned world-range box (ref
  DropLaserPointsOutOfRange:1615)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("keep_x_range", (-np.inf, np.inf), "(min, max) x kept.")
    p.Define("keep_y_range", (-np.inf, np.inf), "(min, max) y kept.")
    p.Define("keep_z_range", (-np.inf, np.inf), "(min, max) z kept.")
    return p

  def _Apply(self, scene, rng):
    del rng
    p = self.p
    xyz = scene.points[:, :3]
    keep = np.ones(xyz.shape[0], bool)
    for dim, (lo, hi) in enumerate(
        (p.keep_x_range, p.keep_y_range, p.keep_z_range)):
      keep &= (xyz[:, dim] >= lo) & (xyz[:, dim] <= hi)
    return _With(scene, points=scene.points[keep])


class DropBoxesOutOfRange(Augmentor):
  """Drop gt boxes whose centers leave the world range (ref :1956) — after
  world rotations/translations some boxes have left the detection range."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("keep_x_range", (-np.inf, np.inf), "(min, max) x kept.")
    p.Define("keep_y_range", (-np.inf, np.inf), "(min, max) y kept.")
    return p

  def _Apply(self, scene, rng):
    del rng
    p = self.p
    if not scene.boxes.size:
      return scene
    c = scene.boxes[:, :2]
    keep = ((c[:, 0] >= p.keep_x_range[0]) & (c[:, 0] <= p.keep_x_range[1]) &
            (c[:, 1] >= p.keep_y_range[0]) & (c[:, 1] <= p.keep_y_range[1]))
    return _KeepBoxes(scene, keep)


class FilterGroundTruthByNumPoints(Augmentor):
  """Drop gt boxes containing fewer than min_num_points lasers (ref :352) —
  a box with no evidence in the point cloud only teaches the detector to
  hallucinate."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("min_num_points", 1, "Boxes with fewer interior points drop.")
    return p

  def _Apply(self, scene, rng):
    del rng
    if not scene.boxes.size:
      return scene
    counts = PointsInBoxes(scene.points, scene.boxes).sum(0)
    keep = counts >= self.p.min_num_points
    return _KeepBoxes(scene, keep)


# ---------------------------------------------------------------------------
# composition
# ---------------------------------------------------------------------------


class RandomApply(Augmentor):
  """Apply the child with probability prob (ref RandomApplyPreprocessor)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("prob", 0.5, "P(apply child).")
    p.Define("subprocessor", None, "Child augmentor Params.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._child = self.p.subprocessor.Instantiate()

  def _Apply(self, scene, rng):
    if rng.uniform() < self.p.prob:
      return self._child.Apply(scene, rng)
    return scene


class RandomChoice(Augmentor):
  """Apply exactly one child, picked by weight (ref
  RandomChoicePreprocessor)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("subprocessors", [], "Child augmentor Params list.")
    p.Define("weights", None, "Selection weights (None = uniform).")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._children = [sp.Instantiate() for sp in self.p.subprocessors]

  def _Apply(self, scene, rng):
    if not self._children:
      return scene
    w = self.p.weights
    probs = None
    if w is not None:
      w = np.asarray(w, np.float64)
      probs = w / w.sum()
    i = int(rng.choice(len(self._children), p=probs))
    return self._children[i].Apply(scene, rng)


class Sequence(Augmentor):
  """Apply children in order (ref Sequence:3527)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("subprocessors", [], "Child augmentor Params list.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._children = [sp.Instantiate() for sp in self.p.subprocessors]

  def _Apply(self, scene, rng):
    for c in self._children:
      scene = c.Apply(scene, rng)
    return scene
