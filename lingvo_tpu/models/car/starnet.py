"""StarNet: sparse, targeted 3-D detection from raw points.

Re-designs `lingvo/tasks/car/starnet.py` (Builder + ModelV1/V2, 908 LoC of
combinator-DSL graph) the TPU way: the same computation — sample centers
from the point cloud, featurize each center's local neighborhood with a
PointNet/GIN-style MLP+max, regress per-anchor box residuals + class
logits — as straight-line JAX with STATIC shapes (fixed center count C,
fixed K nearest neighbors via top_k, dense anchor grids), so the whole
detector jits and shards like any transformer.

Pieces and their reference counterparts:
- `FarthestPointSampling`  <- ref car_lib SamplePoints/FPS
- `NeighborhoodFeaturizer` <- ref Builder.GINFeaturizer (`starnet.py:106`)
- `StarNetModel`           <- ref ModelBase/V1 (`starnet.py:161,516`)
- anchor residual encoding <- ref `_BBoxesAndLogits` (`starnet.py:490`)
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightInit, WeightParams


def FarthestPointSampling(points, paddings, num_samples: int):
  """Greedy FPS: returns indices [b, num_samples] of well-spread points.

  Static-shape iterative selection (lax.fori_loop); padded points are never
  selected (distance forced to -inf).
  """
  b, m, _ = points.shape
  xyz = points[:, :, :3]
  big = 1e9

  def _Body(i, carry):
    idx, min_dist = carry
    # pick the point farthest from the selected set
    masked = jnp.where(paddings > 0, -big, min_dist)
    nxt = jnp.argmax(masked, axis=1)                       # [b]
    idx = idx.at[:, i].set(nxt)
    sel = jnp.take_along_axis(xyz, nxt[:, None, None], axis=1)  # [b,1,3]
    d = jnp.sum((xyz - sel) ** 2, axis=-1)                 # [b, m]
    return idx, jnp.minimum(min_dist, d)

  idx0 = jnp.zeros((b, num_samples), jnp.int32)
  dist0 = jnp.full((b, m), big)
  idx, _ = jax.lax.fori_loop(0, num_samples, _Body, (idx0, dist0))
  return idx


class NeighborhoodFeaturizer(base_layer.BaseLayer):
  """K-nearest points around each center -> MLP -> max-pool feature
  (ref GINFeaturizer, `starnet.py:106`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_neighbors", 16, "K nearest points per center.")
    p.Define("point_dim", 4, "Input point features (xyz + extras).")
    p.Define("mlp_dims", (32, 64), "Per-point MLP widths.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    dims = (p.point_dim + 3,) + tuple(p.mlp_dims)  # +3 relative xyz
    for i in range(len(p.mlp_dims)):
      self.CreateChild(
          f"fc_{i}",
          layers.FCLayer.Params().Set(input_dim=dims[i],
                                      output_dim=dims[i + 1]))

  @property
  def output_dim(self):
    return self.p.mlp_dims[-1]

  def FProp(self, theta, points, paddings, center_idx):
    """points [b,m,d], paddings [b,m], center_idx [b,c] -> [b,c,F]."""
    p = self.p
    xyz = points[:, :, :3]
    centers = jnp.take_along_axis(
        xyz, center_idx[:, :, None], axis=1)               # [b, c, 3]
    d2 = jnp.sum(
        (xyz[:, None, :, :] - centers[:, :, None, :]) ** 2, axis=-1)
    d2 = jnp.where(paddings[:, None, :] > 0, 1e9, d2)      # [b, c, m]
    k = min(p.num_neighbors, d2.shape[-1])  # scenes may have < K points
    _, nn_idx = jax.lax.top_k(-d2, k)                      # [b, c, k]
    nn_pts = jnp.take_along_axis(
        points[:, None], nn_idx[..., None], axis=2)        # [b, c, k, d]
    nn_pad = jnp.take_along_axis(paddings[:, None], nn_idx, axis=2)
    rel = nn_pts[..., :3] - centers[:, :, None, :]
    feats = jnp.concatenate([rel, nn_pts], axis=-1)
    h = feats
    for i in range(len(p.mlp_dims)):
      fc = getattr(self, f"fc_{i}")
      h = fc.FProp(self.ChildTheta(theta, f"fc_{i}"), h)
    h = jnp.where(nn_pad[..., None] > 0, -1e9, h)
    pooled = jnp.max(h, axis=2)                            # [b, c, F]
    # a center whose K neighbors are ALL padding (scene with < K valid
    # points) must emit 0, not -1e9, or it poisons the trunk with inf/NaN
    all_pad = jnp.min(nn_pad, axis=2) > 0                  # [b, c]
    return jnp.where(all_pad[..., None], 0.0, pooled), centers


class StarNetModel(base_model.BaseTask):
  """Sparse targeted detector (ref ModelV1, `starnet.py:516`)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("num_centers", 32, "Sampled anchor centers C.")
    p.Define("num_anchor_rotations", 2, "Anchor rotations per center.")
    p.Define("num_classes", 2, "Foreground classes (background = 0).")
    p.Define("featurizer", NeighborhoodFeaturizer.Params(), "Featurizer.")
    p.Define("hidden_dim", 64, "Post-featurizer FFN width.")
    p.Define("use_atten", True, "Self-attention across cell features "
             "(ref Builder.Atten, starnet.py:89).")
    p.Define("assign_radius", 1.5, "Center-to-GT distance for positives.")
    p.Define("huber_delta", 1.0, "Huber loss transition point.")
    p.Define("nms_radius", 1.0, "Greedy decode suppression radius "
             "(use_oriented_nms=False path).")
    p.Define("max_detections", 8, "Decode output cap per scene (per class "
             "when oriented NMS is on).")
    p.Define("use_oriented_nms", True,
             "Per-class rotated-IoU NMS (detection_3d.DecodeWithNMS, ref "
             "detection_decoder.py) instead of center-distance suppression.")
    p.Define("nms_iou_threshold", 0.3, "Rotated-IoU suppression threshold.")
    p.Define("nms_score_threshold", 0.01, "Min score to enter NMS.")
    return p

  def __init__(self, params, **kwargs):
    super().__init__(params, **kwargs)
    p = self.p
    self.CreateChild("featurizer", p.featurizer.Copy())
    f = self.featurizer.output_dim
    self.CreateChild(
        "trunk",
        layers.FeedForwardNet.Params().Set(
            input_dim=f, hidden_layer_dims=(p.hidden_dim, p.hidden_dim),
            activation="RELU"))
    if p.use_atten:
      from lingvo_tpu.core import attention as attention_lib
      self.CreateChild(
          "atten",
          attention_lib.MultiHeadedAttention.Params().Set(
              input_dim=p.hidden_dim, hidden_dim=p.hidden_dim, num_heads=2))
    a = p.num_anchor_rotations
    self.CreateChild(
        "cls_head",
        layers.ProjectionLayer.Params().Set(
            input_dim=p.hidden_dim, output_dim=a * (p.num_classes + 1),
            params_init=WeightInit.Gaussian(0.01)))
    self.CreateChild(
        "reg_head",
        layers.ProjectionLayer.Params().Set(
            input_dim=p.hidden_dim, output_dim=a * 7,
            params_init=WeightInit.Gaussian(0.01)))

  def _AnchorRotations(self):
    a = self.p.num_anchor_rotations
    return jnp.arange(a) * (math.pi / a)

  def ComputePredictions(self, theta, batch):
    p = self.p
    center_idx = FarthestPointSampling(batch.lasers, batch.laser_paddings,
                                       p.num_centers)
    feats, centers = self.featurizer.FProp(
        self.ChildTheta(theta, "featurizer"), batch.lasers,
        batch.laser_paddings, center_idx)
    h = self.trunk.FProp(self.ChildTheta(theta, "trunk"), feats)
    if p.use_atten:
      out, _ = self.atten.FProp(self.ChildTheta(theta, "atten"), h)
      h = h + out
    b, c = h.shape[0], h.shape[1]
    a = p.num_anchor_rotations
    cls_logits = self.cls_head.FProp(
        self.ChildTheta(theta, "cls_head"), h).reshape(
            b, c, a, p.num_classes + 1)
    residuals = self.reg_head.FProp(
        self.ChildTheta(theta, "reg_head"), h).reshape(b, c, a, 7)
    return NestedMap(centers=centers, cls_logits=cls_logits,
                     residuals=residuals)

  def _AssignTargets(self, centers, gt_boxes, gt_classes):
    """Nearest-GT assignment within assign_radius (per center)."""
    p = self.p
    gt_xy = gt_boxes[:, :, :2]                              # [b, n, 2]
    d2 = jnp.sum(
        (centers[:, :, None, :2] - gt_xy[:, None, :, :]) ** 2, axis=-1)
    # mask out empty GT slots (class 0)
    d2 = jnp.where(gt_classes[:, None, :] == 0, 1e9, d2)
    best = jnp.argmin(d2, axis=2)                           # [b, c]
    best_d2 = jnp.min(d2, axis=2)
    fg = best_d2 < p.assign_radius ** 2                     # [b, c]
    box = jnp.take_along_axis(gt_boxes, best[:, :, None], axis=1)
    cls = jnp.take_along_axis(gt_classes, best, axis=1)
    return fg, box, jnp.where(fg, cls, 0)

  def _EncodeResiduals(self, centers, boxes, rot):
    """Target residuals per anchor rotation: [b, c, a, 7]."""
    b, c = centers.shape[0], centers.shape[1]
    a = rot.shape[0]
    delta_xyz = jnp.broadcast_to(
        boxes[:, :, None, :3] - centers[:, :, None, :], (b, c, a, 3))
    dims = jnp.broadcast_to(jnp.log(jnp.maximum(boxes[:, :, None, 3:6],
                                                1e-3)), (b, c, a, 3))
    dtheta = boxes[:, :, None, 6:7] - rot[None, None, :, None]  # [b,c,a,1]
    return jnp.concatenate([delta_xyz, dims, dtheta], axis=-1)

  def ComputeLoss(self, theta, preds, batch):
    p = self.p
    fg, box, cls = self._AssignTargets(preds.centers, batch.gt_boxes,
                                       batch.gt_classes)
    rot = self._AnchorRotations()
    reg_t = self._EncodeResiduals(preds.centers, box, rot)

    # classification: every anchor learns; positives carry the box class
    cls_target = jnp.broadcast_to(cls[:, :, None],
                                  preds.cls_logits.shape[:3])
    logp = jax.nn.log_softmax(preds.cls_logits.astype(jnp.float32), -1)
    cls_loss = -jnp.take_along_axis(logp, cls_target[..., None],
                                    axis=-1)[..., 0]
    cls_loss = jnp.mean(cls_loss)

    # regression: huber on foreground anchors only
    err = (preds.residuals.astype(jnp.float32) - reg_t)
    abs_err = jnp.abs(err)
    huber = jnp.where(abs_err < p.huber_delta, 0.5 * err ** 2,
                      p.huber_delta * (abs_err - 0.5 * p.huber_delta))
    w = fg[:, :, None, None].astype(jnp.float32)
    reg_loss = jnp.sum(huber * w) / jnp.maximum(jnp.sum(w) * 7, 1.0)

    loss = cls_loss + reg_loss
    n = batch.lasers.shape[0]
    return NestedMap(
        loss=(loss, n), cls_loss=(cls_loss, n), reg_loss=(reg_loss, n)), \
        NestedMap()

  def Decode(self, theta, batch):
    p = self.p
    preds = self.ComputePredictions(theta, batch)
    probs = jax.nn.softmax(preds.cls_logits.astype(jnp.float32), -1)
    fg_probs = probs[..., 1:]                                # [b,c,a,K]
    score = jnp.max(fg_probs, axis=(2, 3))                   # [b, c]
    best_a = jnp.argmax(jnp.max(fg_probs, axis=3), axis=2)   # [b, c]
    best_k = jnp.argmax(jnp.max(fg_probs, axis=2), axis=2) + 1
    res = jnp.take_along_axis(preds.residuals, best_a[:, :, None, None],
                              axis=2)[:, :, 0]               # [b, c, 7]
    rot = self._AnchorRotations()[best_a]                    # [b, c]
    boxes = jnp.concatenate(
        [preds.centers + res[..., :3], jnp.exp(res[..., 3:6]),
         (res[..., 6] + rot)[..., None]], axis=-1)           # [b, c, 7]

    if p.use_oriented_nms:
      from lingvo_tpu.models.car import detection_3d
      # per-center class distribution (best anchor rotation's view)
      cls_probs = jnp.concatenate(
          [probs[..., 0:1].min(axis=2), jnp.max(probs[..., 1:], axis=2)],
          axis=-1)                                           # [b, c, K+1]
      det = detection_3d.DecodeWithNMS(
          boxes, cls_probs, nms_iou_threshold=p.nms_iou_threshold,
          score_threshold=p.nms_score_threshold,
          max_boxes_per_class=p.max_detections)
      b = boxes.shape[0]
      ncls = cls_probs.shape[-1]
      # flatten per-class outputs; padded slots carry score 0 (filtered in
      # postprocess, same contract as the center-distance path)
      out_boxes = det.bboxes[:, 1:].reshape(b, -1, 7)
      out_scores = det.scores[:, 1:].reshape(b, -1)
      cls_ids = jnp.broadcast_to(
          jnp.arange(1, ncls)[None, :, None],
          (b, ncls - 1, p.max_detections)).reshape(b, -1)
      return NestedMap(boxes=out_boxes, scores=out_scores,
                       classes=cls_ids.astype(jnp.int32),
                       gt_boxes=batch.gt_boxes, gt_classes=batch.gt_classes)

    # greedy center-distance NMS with static iteration count; suppressed
    # entries go to -1 so exhausted scenes emit score<=0 slots (filtered in
    # postprocess) instead of duplicating box 0
    def _Nms(scores, boxes):
      keep = jnp.zeros((p.max_detections,), jnp.int32)
      keep_scores = jnp.zeros((p.max_detections,), jnp.float32)

      def _Body(i, carry):
        keep, keep_scores, working = carry
        best = jnp.argmax(working)
        keep = keep.at[i].set(best)
        keep_scores = keep_scores.at[i].set(jnp.maximum(working[best], 0.0))
        d2 = jnp.sum((boxes[:, :2] - boxes[best, :2]) ** 2, -1)
        working = jnp.where(d2 <= p.nms_radius ** 2, -1.0, working)
        return keep, keep_scores, working

      keep, keep_scores, _ = jax.lax.fori_loop(
          0, p.max_detections, _Body, (keep, keep_scores, scores))
      return keep, keep_scores

    keep, out_scores = jax.vmap(_Nms)(score, boxes)          # [b, D]
    out_boxes = jnp.take_along_axis(boxes, keep[:, :, None], axis=1)
    out_cls = jnp.take_along_axis(best_k, keep, axis=1)
    return NestedMap(boxes=out_boxes, scores=out_scores, classes=out_cls,
                     gt_boxes=batch.gt_boxes, gt_classes=batch.gt_classes)

  def CreateDecoderMetrics(self):
    from lingvo_tpu.models.car import ap_metric
    return {"ap": ap_metric.ApMetric()}

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    import numpy as np
    boxes = np.asarray(decode_out.boxes)
    scores = np.asarray(decode_out.scores)
    classes = np.asarray(decode_out.classes)
    gt_boxes = np.asarray(decode_out.gt_boxes)
    gt_classes = np.asarray(decode_out.gt_classes)
    for i in range(boxes.shape[0]):
      gt_mask = gt_classes[i] > 0
      valid = scores[i] > 0.0  # NMS pads exhausted scenes with score 0
      decoder_metrics["ap"].Update(boxes[i][valid], scores[i][valid],
                                   gt_boxes[i][gt_mask],
                                   pred_classes=classes[i][valid],
                                   gt_classes=gt_classes[i][gt_mask])

  def DecodeFinalize(self, decoder_metrics):
    return {"ap": decoder_metrics["ap"].value}
