"""Detection calibration: reliability curves + expected calibration error
(ref `lingvo/tasks/car/calibration_processing.py` CalibrationCurve /
ExpectedCalibrationError / CalibrationCalculator).

Consumes the same (score, hit) stream ApMetric accumulates: a detection's
confidence should predict its probability of matching a ground truth.
"""

from __future__ import annotations

import numpy as np


def CalibrationCurve(scores: np.ndarray, hits: np.ndarray,
                     num_bins: int = 10):
  """(scores [N], hits [N] 0/1) -> (mean_predicted, mean_empirical,
  num_examples) per score bin (ref CalibrationCurve; bin 0 is skipped,
  zero scores land in bin 1)."""
  scores = np.asarray(scores, np.float64)
  hits = np.asarray(hits, np.float64)
  bad = ~np.isfinite(scores)
  if bad.any():
    import warnings
    warnings.warn(
        f"{int(bad.sum())} non-finite calibration scores dropped — "
        "pass probabilities")
    scores, hits = scores[~bad], hits[~bad]
  if scores.size and (scores.min() < 0.0 or scores.max() > 1.0):
    # unsigmoided logits fed as 'scores' would silently fall outside every
    # bin and shrink the ECE; clip (and warn) so every detection is counted
    import warnings
    warnings.warn(
        f"calibration scores outside [0, 1] (min={scores.min():.3g}, "
        f"max={scores.max():.3g}); clipping — pass probabilities")
    scores = np.clip(scores, 0.0, 1.0)
  edges = np.linspace(0.0, 1.0, num_bins + 1)
  bin_indices = np.digitize(scores, edges, right=True)
  bin_indices = np.where(scores == 0.0, 1, bin_indices)
  mean_pred, mean_emp, counts = [], [], []
  for j in range(1, num_bins + 1):
    idx = np.where(bin_indices == j)[0]
    if len(idx):
      mean_pred.append(float(np.mean(scores[idx])))
      mean_emp.append(float(np.mean(hits[idx])))
      counts.append(len(idx))
    else:
      mean_pred.append(float((edges[j - 1] + edges[j]) / 2.0))
      mean_emp.append(0.0)
      counts.append(0)
  return np.asarray(mean_pred), np.asarray(mean_emp), np.asarray(counts)


def ExpectedCalibrationError(confidence: np.ndarray,
                             empirical_accuracy: np.ndarray,
                             num_examples: np.ndarray,
                             min_confidence: float | None = None) -> float:
  """Count-weighted mean |empirical - predicted| over bins (ref
  ExpectedCalibrationError)."""
  confidence = np.asarray(confidence, np.float64)
  empirical_accuracy = np.asarray(empirical_accuracy, np.float64)
  num_examples = np.asarray(num_examples, np.float64)
  ece = np.abs(empirical_accuracy - confidence) * num_examples
  if min_confidence is not None:
    keep = confidence > min_confidence
    ece = ece[keep]
    num_examples = num_examples[keep]
  total = float(np.sum(num_examples))
  return float(np.sum(ece) / total) if total else 0.0


class CalibrationMetric:
  """Accumulates (score, hit) detections; value = ECE.

  Feed directly via Update, or adopt an ApMetric's match stream with
  FromApMetric (the reference's CalibrationCalculator consumes the same
  per-detection (prob, matched) pairs the AP pipeline produces).
  """

  def __init__(self, num_bins: int = 10,
               min_confidence: float | None = None):
    self._num_bins = num_bins
    self._min_confidence = min_confidence
    self._scores: list[float] = []
    self._hits: list[float] = []

  def Update(self, scores, hits) -> None:
    self._scores.extend(float(s) for s in np.ravel(scores))
    self._hits.extend(float(h) for h in np.ravel(hits))

  def FromApMetric(self, ap_metric) -> "CalibrationMetric":
    for score, matched in ap_metric.detections:
      self._scores.append(float(score))
      self._hits.append(1.0 if matched else 0.0)
    return self

  @property
  def curve(self):
    return CalibrationCurve(np.asarray(self._scores),
                            np.asarray(self._hits), self._num_bins)

  @property
  def value(self) -> float:
    if not self._scores:
      return 0.0
    mean_pred, mean_emp, counts = self.curve
    return ExpectedCalibrationError(mean_pred, mean_emp, counts,
                                    self._min_confidence)

  @property
  def total_weight(self) -> float:
    return float(len(self._scores))
