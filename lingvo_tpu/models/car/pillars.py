"""Point-cloud 3D detection: PointPillars-style slice of the car family
(ref `lingvo/tasks/car/` — StarNet/PointPillars models, `pillars.py`,
`point_detector.py`; the 22k-LoC reference also carries KITTI/Waymo
pipelines and extensive geometry libs, which enter as data prep here).

TPU-first shapes: the pillar featurizer is a per-point MLP + masked
max-pool (batched matmuls), the scatter of pillar features onto the BEV
grid is a one-hot einsum (MXU-friendly; no data-dependent scatter), and
the backbone/head are dense convs — everything static-shape under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap


class PillarFeaturizer(base_layer.BaseLayer):
  """Per-point MLP + masked max-pool per pillar (ref PointNet featurizer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("point_dim", 0, "Per-point input features.")
    p.Define("feature_dim", 64, "Pillar feature dim C.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "mlp",
        layers_lib.FeedForwardNet.Params().Set(
            input_dim=p.point_dim,
            hidden_layer_dims=[p.feature_dim, p.feature_dim]))

  def FProp(self, theta, pillar_points, point_paddings):
    """[b, P, N, D], [b, P, N] -> pillar features [b, P, C]."""
    feats = self.mlp.FProp(theta.mlp, pillar_points)      # [b,P,N,C]
    masked = jnp.where(point_paddings[..., None] > 0.5, -1e9, feats)
    pooled = jnp.max(masked, axis=2)
    # pillars with zero points pool to -1e9: zero them
    any_point = jnp.any(point_paddings < 0.5, axis=2, keepdims=True)
    return jnp.where(any_point, pooled, 0.0)


class BevBackboneHead(base_layer.BaseLayer):
  """Scatter to BEV + conv backbone + per-cell detection head."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("grid_size", 16, "BEV grid is grid_size x grid_size.")
    p.Define("feature_dim", 64, "Input pillar feature dim.")
    p.Define("num_classes", 2, "Foreground classes (0 = background).")
    p.Define("box_dims", 7, "Box residual dims (x,y,z,l,w,h,theta).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    c = p.feature_dim
    self.CreateChild(
        "conv1",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, c, c), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "conv2",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, c, c), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "cls_head",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=c, output_dim=p.num_classes + 1))
    self.CreateChild(
        "reg_head",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=c, output_dim=p.box_dims))

  def FProp(self, theta, pillar_feats, pillar_cells):
    """pillar_feats [b, P, C], pillar_cells [b, P] (flat BEV cell index or
    -1 for empty) -> (cls_logits [b, G*G, K+1], box_residuals [b, G*G, 7])."""
    p = self.p
    g2 = p.grid_size * p.grid_size
    valid = (pillar_cells >= 0)
    one_hot = jax.nn.one_hot(
        jnp.where(valid, pillar_cells, 0), g2,
        dtype=pillar_feats.dtype)                          # [b,P,G2]
    one_hot = one_hot * valid[..., None].astype(one_hot.dtype)
    # scatter-as-einsum: multiple pillars in one cell SUM their features
    bev = jnp.einsum("bpc,bpg->bgc", pillar_feats, one_hot)
    b = bev.shape[0]
    img = bev.reshape(b, p.grid_size, p.grid_size, -1)
    img = self.conv1.FProp(theta.conv1, img)
    img = self.conv2.FProp(theta.conv2, img)
    flat = img.reshape(b, g2, -1)
    return (self.cls_head.FProp(theta.cls_head, flat),
            self.reg_head.FProp(theta.reg_head, flat))


class PointPillarsModel(base_model.BaseTask):
  """Single-anchor-per-cell detector.

  Batch contract (targets precomputed by the input pipeline like the
  reference's KITTI loaders):
    pillar_points [b,P,N,D], point_paddings [b,P,N], pillar_cells [b,P]
    cls_targets [b, G*G] int (0=background), reg_targets [b, G*G, 7],
    reg_weights [b, G*G] (1 on positive cells)
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("featurizer", PillarFeaturizer.Params(), "Pillar featurizer.")
    p.Define("backbone", BevBackboneHead.Params(), "BEV backbone + heads.")
    p.Define("reg_loss_weight", 2.0, "Box regression loss weight.")
    p.Define("num_boxes_to_decode", 8, "Top-k boxes emitted by Decode.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("featurizer", self.p.featurizer)
    self.CreateChild("backbone", self.p.backbone)

  def ComputePredictions(self, theta, input_batch):
    feats = self.featurizer.FProp(
        self.ChildTheta(theta, "featurizer"), input_batch.pillar_points,
        input_batch.point_paddings)
    cls_logits, reg = self.backbone.FProp(
        self.ChildTheta(theta, "backbone"), feats, input_batch.pillar_cells)
    return NestedMap(cls_logits=cls_logits, box_residuals=reg)

  def ComputeLoss(self, theta, predictions, input_batch):
    cls_logits = predictions.cls_logits.astype(jnp.float32)
    num_classes = cls_logits.shape[-1]
    onehot = jax.nn.one_hot(input_batch.cls_targets, num_classes)
    cls_loss = -jnp.sum(
        onehot * jax.nn.log_softmax(cls_logits, -1), -1)   # [b, G2]
    # focal-style down-weighting of easy negatives (ref car losses)
    probs = jax.nn.softmax(cls_logits, -1)
    pt = jnp.sum(onehot * probs, -1)
    cls_loss = cls_loss * (1.0 - pt) ** 2
    cls_loss = jnp.mean(cls_loss)

    diff = (predictions.box_residuals.astype(jnp.float32)
            - input_batch.reg_targets)
    huber = jnp.where(jnp.abs(diff) < 1.0, 0.5 * diff * diff,
                      jnp.abs(diff) - 0.5)
    w = input_batch.reg_weights
    reg_loss = jnp.sum(huber.sum(-1) * w) / jnp.maximum(jnp.sum(w), 1.0)

    total = cls_loss + self.p.reg_loss_weight * reg_loss
    b = float(cls_logits.shape[0])
    return NestedMap(loss=(total, b), cls_loss=(cls_loss, b),
                     reg_loss=(reg_loss, b)), NestedMap()

  def Decode(self, theta, input_batch):
    preds = self.ComputePredictions(theta, input_batch)
    probs = jax.nn.softmax(preds.cls_logits.astype(jnp.float32), -1)
    fg_score = 1.0 - probs[..., 0]                         # [b, G2]
    k = self.p.num_boxes_to_decode
    top_scores, top_cells = jax.lax.top_k(fg_score, k)
    top_boxes = jnp.take_along_axis(preds.box_residuals,
                                    top_cells[..., None], axis=1)
    top_cls = jnp.take_along_axis(jnp.argmax(probs, -1), top_cells, axis=1)
    return NestedMap(scores=top_scores, cells=top_cells, boxes=top_boxes,
                     classes=top_cls,
                     gt_cls_targets=input_batch.cls_targets,
                     gt_reg_targets=input_batch.reg_targets,
                     gt_reg_weights=input_batch.reg_weights)

  def CreateDecoderMetrics(self):
    from lingvo_tpu.core import metrics as metrics_lib
    from lingvo_tpu.models.car import ap_metric
    return {"cell_precision": metrics_lib.AverageMetric(),
            "cell_recall": metrics_lib.AverageMetric(),
            "ap": ap_metric.ApMetric(iou_threshold=0.5)}

  def _CellToBox(self, cell: int, residual) -> list:
    """Cell index + [dx, dy, z, l, w, h, theta] residual -> BEV rotated
    box [cx, cy, l, w, theta] (the target-encoding inverse)."""
    g = self.p.backbone.grid_size
    cy, cx = divmod(int(cell), g)
    return [cx + 0.5 + float(residual[0]), cy + 0.5 + float(residual[1]),
            float(residual[3]), float(residual[4]), float(residual[6])]

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    """Cell-level precision/recall at score>0.5 + rotated-IoU AP@0.5
    (ref ap_metric.py)."""
    scores = np.asarray(decode_out.scores)
    cells = np.asarray(decode_out.cells)
    boxes = np.asarray(decode_out.boxes)
    gt = np.asarray(decode_out.gt_cls_targets)
    gt_reg = np.asarray(decode_out.gt_reg_targets)
    gt_w = np.asarray(decode_out.gt_reg_weights)
    for i in range(scores.shape[0]):
      pred_cells = set(cells[i][scores[i] > 0.5].tolist())
      gt_cells = set(np.nonzero(gt[i])[0].tolist())
      if pred_cells:
        decoder_metrics["cell_precision"].Update(
            len(pred_cells & gt_cells) / len(pred_cells))
      if gt_cells:
        decoder_metrics["cell_recall"].Update(
            len(pred_cells & gt_cells) / len(gt_cells))
      # rotated-IoU AP over decoded absolute boxes
      pred_boxes = np.asarray(
          [self._CellToBox(cells[i, k], boxes[i, k])
           for k in range(cells.shape[1])])
      gt_boxes = np.asarray(
          [self._CellToBox(c, gt_reg[i, c])
           for c in np.nonzero(gt_w[i] > 0)[0]])
      decoder_metrics["ap"].Update(pred_boxes, scores[i], gt_boxes)
