"""Point-cloud 3D detection: PointPillars-style slice of the car family
(ref `lingvo/tasks/car/` — StarNet/PointPillars models, `pillars.py`,
`point_detector.py`; the 22k-LoC reference also carries KITTI/Waymo
pipelines and extensive geometry libs, which enter as data prep here).

TPU-first shapes: the pillar featurizer is a per-point MLP + masked
max-pool (batched matmuls), the scatter of pillar features onto the BEV
grid is a one-hot einsum (MXU-friendly; no data-dependent scatter), and
the backbone/head are dense convs — everything static-shape under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap


class PillarFeaturizer(base_layer.BaseLayer):
  """Per-point MLP + masked max-pool per pillar (ref PointNet featurizer)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("point_dim", 0, "Per-point input features.")
    p.Define("feature_dim", 64, "Pillar feature dim C.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "mlp",
        layers_lib.FeedForwardNet.Params().Set(
            input_dim=p.point_dim,
            hidden_layer_dims=[p.feature_dim, p.feature_dim]))

  def FProp(self, theta, pillar_points, point_paddings):
    """[b, P, N, D], [b, P, N] -> pillar features [b, P, C]."""
    feats = self.mlp.FProp(theta.mlp, pillar_points)      # [b,P,N,C]
    masked = jnp.where(point_paddings[..., None] > 0.5, -1e9, feats)
    pooled = jnp.max(masked, axis=2)
    # pillars with zero points pool to -1e9: zero them
    any_point = jnp.any(point_paddings < 0.5, axis=2, keepdims=True)
    return jnp.where(any_point, pooled, 0.0)


class BevBackboneHead(base_layer.BaseLayer):
  """Scatter to BEV + conv backbone + per-cell detection head."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("grid_size", 16, "BEV grid is grid_size x grid_size.")
    p.Define("feature_dim", 64, "Input pillar feature dim.")
    p.Define("num_classes", 2, "Foreground classes (0 = background).")
    p.Define("box_dims", 7, "Box residual dims (x,y,z,l,w,h,theta).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    c = p.feature_dim
    self.CreateChild(
        "conv1",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, c, c), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "conv2",
        layers_lib.Conv2DLayer.Params().Set(
            filter_shape=(3, 3, c, c), filter_stride=(1, 1),
            activation="RELU", batch_norm=False, has_bias=True))
    self.CreateChild(
        "cls_head",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=c, output_dim=p.num_classes + 1))
    self.CreateChild(
        "reg_head",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=c, output_dim=p.box_dims))

  def FProp(self, theta, pillar_feats, pillar_cells):
    """pillar_feats [b, P, C], pillar_cells [b, P] (flat BEV cell index or
    -1 for empty) -> (cls_logits [b, G*G, K+1], box_residuals [b, G*G, 7])."""
    p = self.p
    g2 = p.grid_size * p.grid_size
    valid = (pillar_cells >= 0)
    one_hot = jax.nn.one_hot(
        jnp.where(valid, pillar_cells, 0), g2,
        dtype=pillar_feats.dtype)                          # [b,P,G2]
    one_hot = one_hot * valid[..., None].astype(one_hot.dtype)
    # scatter-as-einsum: multiple pillars in one cell SUM their features
    bev = jnp.einsum("bpc,bpg->bgc", pillar_feats, one_hot)
    b = bev.shape[0]
    img = bev.reshape(b, p.grid_size, p.grid_size, -1)
    img = self.conv1.FProp(theta.conv1, img)
    img = self.conv2.FProp(theta.conv2, img)
    flat = img.reshape(b, g2, -1)
    return (self.cls_head.FProp(theta.cls_head, flat),
            self.reg_head.FProp(theta.reg_head, flat))


class PointPillarsModel(base_model.BaseTask):
  """Single-anchor-per-cell detector.

  Batch contract (targets precomputed by the input pipeline like the
  reference's KITTI loaders):
    pillar_points [b,P,N,D], point_paddings [b,P,N], pillar_cells [b,P]
    cls_targets [b, G*G] int (0=background), reg_targets [b, G*G, 7],
    reg_weights [b, G*G] (1 on positive cells)
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("featurizer", PillarFeaturizer.Params(), "Pillar featurizer.")
    p.Define("backbone", BevBackboneHead.Params(), "BEV backbone + heads.")
    p.Define("reg_loss_weight", 2.0, "Box regression loss weight.")
    p.Define("num_boxes_to_decode", 8, "Top-k boxes emitted by Decode.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("featurizer", self.p.featurizer)
    self.CreateChild("backbone", self.p.backbone)

  def ComputePredictions(self, theta, input_batch):
    feats = self.featurizer.FProp(
        self.ChildTheta(theta, "featurizer"), input_batch.pillar_points,
        input_batch.point_paddings)
    cls_logits, reg = self.backbone.FProp(
        self.ChildTheta(theta, "backbone"), feats, input_batch.pillar_cells)
    return NestedMap(cls_logits=cls_logits, box_residuals=reg)

  def ComputeLoss(self, theta, predictions, input_batch):
    cls_logits = predictions.cls_logits.astype(jnp.float32)
    num_classes = cls_logits.shape[-1]
    onehot = jax.nn.one_hot(input_batch.cls_targets, num_classes)
    cls_loss = -jnp.sum(
        onehot * jax.nn.log_softmax(cls_logits, -1), -1)   # [b, G2]
    # focal-style down-weighting of easy negatives (ref car losses)
    probs = jax.nn.softmax(cls_logits, -1)
    pt = jnp.sum(onehot * probs, -1)
    cls_loss = cls_loss * (1.0 - pt) ** 2
    cls_loss = jnp.mean(cls_loss)

    diff = (predictions.box_residuals.astype(jnp.float32)
            - input_batch.reg_targets)
    huber = jnp.where(jnp.abs(diff) < 1.0, 0.5 * diff * diff,
                      jnp.abs(diff) - 0.5)
    w = input_batch.reg_weights
    reg_loss = jnp.sum(huber.sum(-1) * w) / jnp.maximum(jnp.sum(w), 1.0)

    total = cls_loss + self.p.reg_loss_weight * reg_loss
    b = float(cls_logits.shape[0])
    return NestedMap(loss=(total, b), cls_loss=(cls_loss, b),
                     reg_loss=(reg_loss, b)), NestedMap()

  def Decode(self, theta, input_batch):
    preds = self.ComputePredictions(theta, input_batch)
    probs = jax.nn.softmax(preds.cls_logits.astype(jnp.float32), -1)
    fg_score = 1.0 - probs[..., 0]                         # [b, G2]
    k = self.p.num_boxes_to_decode
    top_scores, top_cells = jax.lax.top_k(fg_score, k)
    top_boxes = jnp.take_along_axis(preds.box_residuals,
                                    top_cells[..., None], axis=1)
    top_cls = jnp.take_along_axis(jnp.argmax(probs, -1), top_cells, axis=1)
    return NestedMap(scores=top_scores, cells=top_cells, boxes=top_boxes,
                     classes=top_cls,
                     gt_cls_targets=input_batch.cls_targets,
                     gt_reg_targets=input_batch.reg_targets,
                     gt_reg_weights=input_batch.reg_weights)

  def CreateDecoderMetrics(self):
    from lingvo_tpu.core import metrics as metrics_lib
    from lingvo_tpu.models.car import ap_metric
    return {"cell_precision": metrics_lib.AverageMetric(),
            "cell_recall": metrics_lib.AverageMetric(),
            "ap": ap_metric.ApMetric(iou_threshold=0.5)}

  def _CellToBox(self, cell: int, residual) -> list:
    """Cell index + [dx, dy, z, l, w, h, theta] residual -> BEV rotated
    box [cx, cy, l, w, theta] (the target-encoding inverse)."""
    g = self.p.backbone.grid_size
    cy, cx = divmod(int(cell), g)
    return [cx + 0.5 + float(residual[0]), cy + 0.5 + float(residual[1]),
            float(residual[3]), float(residual[4]), float(residual[6])]

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    """Cell-level precision/recall at score>0.5 + rotated-IoU AP@0.5
    (ref ap_metric.py)."""
    scores = np.asarray(decode_out.scores)
    cells = np.asarray(decode_out.cells)
    boxes = np.asarray(decode_out.boxes)
    gt = np.asarray(decode_out.gt_cls_targets)
    gt_reg = np.asarray(decode_out.gt_reg_targets)
    gt_w = np.asarray(decode_out.gt_reg_weights)
    for i in range(scores.shape[0]):
      pred_cells = set(cells[i][scores[i] > 0.5].tolist())
      gt_cells = set(np.nonzero(gt[i])[0].tolist())
      if pred_cells:
        decoder_metrics["cell_precision"].Update(
            len(pred_cells & gt_cells) / len(pred_cells))
      if gt_cells:
        decoder_metrics["cell_recall"].Update(
            len(pred_cells & gt_cells) / len(gt_cells))
      # rotated-IoU AP over decoded absolute boxes
      pred_boxes = np.asarray(
          [self._CellToBox(cells[i, k], boxes[i, k])
           for k in range(cells.shape[1])])
      gt_boxes = np.asarray(
          [self._CellToBox(c, gt_reg[i, c])
           for c in np.nonzero(gt_w[i] > 0)[0]])
      decoder_metrics["ap"].Update(pred_boxes, scores[i], gt_boxes)


def HeatMapPeaks(heat: jax.Array, kernel_size: int = 3) -> jax.Array:
  """Keeps only local maxima of a [b, gx, gy, k] heatmap (values elsewhere
  0) — the heatmap-NMS decode (ref pillars_anchor_free.py HeatMapNMS:41,
  max-pool + equality mask). Pure XLA reduce_window: no data-dependent
  control flow."""
  pooled = jax.lax.reduce_window(
      heat, -jnp.inf, jax.lax.max,
      window_dimensions=(1, kernel_size, kernel_size, 1),
      window_strides=(1, 1, 1, 1), padding="SAME")
  return jnp.where(heat >= pooled, heat, 0.0)


class AnchorFreePillarsModel(PointPillarsModel):
  """Anchor-free (CenterNet-style) pillars detector (ref
  `lingvo/tasks/car/pillars_anchor_free.py:1-1027` ModelV2: class heat map
  + centerness + per-cell regression, heat-map NMS decode — no anchor
  grid, no box-level NMS).

  Reuses the anchored model's featurizer/backbone and the SAME input
  targets (cls_targets marks each gt's center cell): the gaussian heat-map
  targets are splatted ON DEVICE from the center cells + box sizes, so the
  input pipeline needs no new fields. Losses: penalty-reduced focal
  sigmoid on the heat map (CenterNet eq. 1), huber on center-cell box
  residuals, optional centerness BCE against the gaussian value.
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("focal_alpha", 2.0, "Focal exponent on |1 - p|.")
    p.Define("focal_beta", 4.0, "Penalty reduction near centers.")
    p.Define("min_gaussian_sigma", 0.8,
             "Sigma floor (cells) for the target splat.")
    p.Define("centerness_loss_weight", 0.2,
             "Weight of the centerness head loss (0 disables the head; "
             "ref pillars_anchor_free.py centerness_loss_weight).")
    p.Define("peak_kernel_size", 3, "Heat-map NMS pooling window.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    c = p.backbone.feature_dim
    k = p.backbone.num_classes
    # class heat map has NO background channel (sigmoid per class); the
    # inherited cls_head (softmax K+1) goes unused but stays in theta for
    # head-swap warm starts
    self.CreateChild(
        "heat_head",
        layers_lib.ProjectionLayer.Params().Set(input_dim=c, output_dim=k))
    if p.centerness_loss_weight > 0:
      self.CreateChild(
          "centerness_head",
          layers_lib.ProjectionLayer.Params().Set(input_dim=c,
                                                  output_dim=1))

  def _BackboneFeatures(self, theta, input_batch):
    bb = self.backbone
    feats = self.featurizer.FProp(
        self.ChildTheta(theta, "featurizer"), input_batch.pillar_points,
        input_batch.point_paddings)
    p = bb.p
    g2 = p.grid_size * p.grid_size
    valid = (input_batch.pillar_cells >= 0)
    one_hot = jax.nn.one_hot(
        jnp.where(valid, input_batch.pillar_cells, 0), g2,
        dtype=feats.dtype) * valid[..., None].astype(feats.dtype)
    bev = jnp.einsum("bpc,bpg->bgc", feats, one_hot)
    b = bev.shape[0]
    img = bev.reshape(b, p.grid_size, p.grid_size, -1)
    img = bb.conv1.FProp(self.ChildTheta(theta, "backbone").conv1, img)
    img = bb.conv2.FProp(self.ChildTheta(theta, "backbone").conv2, img)
    return img.reshape(b, g2, -1)

  def ComputePredictions(self, theta, input_batch):
    p = self.p
    flat = self._BackboneFeatures(theta, input_batch)
    preds = NestedMap(
        heat_logits=self.heat_head.FProp(
            self.ChildTheta(theta, "heat_head"), flat),
        box_residuals=self.backbone.reg_head.FProp(
            self.ChildTheta(theta, "backbone").reg_head, flat))
    if p.centerness_loss_weight > 0:
      preds.centerness_logits = self.centerness_head.FProp(
          self.ChildTheta(theta, "centerness_head"), flat)[..., 0]
    return preds

  def _GaussianTargets(self, input_batch):
    """[b, G2, K] heat-map targets: per class, max over gt centers of
    exp(-d^2 / 2 sigma^2), sigma from the box BEV footprint (cells)."""
    p = self.p
    g = p.backbone.grid_size
    k = p.backbone.num_classes
    cls_t = input_batch.cls_targets                     # [b, G2] 0=bg
    reg_t = input_batch.reg_targets                     # [b, G2, 7]
    pos = (cls_t > 0).astype(jnp.float32)               # [b, G2]
    idx = jnp.arange(g * g)
    cy, cx = idx // g, idx % g                          # [G2]
    # pairwise squared cell distance [G2 cells, G2 centers]
    d2 = ((cx[:, None] - cx[None, :]) ** 2
          + (cy[:, None] - cy[None, :]) ** 2).astype(jnp.float32)
    # sigma per center cell from the box diagonal (l, w are world units;
    # the grid targets carry them in reg_targets[3:5] — scale to cells via
    # the implied cell count; min floor keeps single-cell objects learnable)
    sigma = jnp.maximum(
        jnp.sqrt(reg_t[..., 3] ** 2 + reg_t[..., 4] ** 2) / 6.0,
        p.min_gaussian_sigma)                           # [b, G2]
    gauss = jnp.exp(-d2[None] / (2.0 * (sigma[:, None, :] ** 2)))
    gauss = gauss * pos[:, None, :]                     # zero non-centers
    onehot_k = jax.nn.one_hot(cls_t - 1, k) * pos[..., None]   # [b,G2,K]
    # [b, G2 cells, K]: max over centers of that class
    return jnp.max(gauss[..., None] * onehot_k[:, None], axis=2)

  def ComputeLoss(self, theta, predictions, input_batch):
    p = self.p
    heat_logits = predictions.heat_logits.astype(jnp.float32)
    targets = self._GaussianTargets(input_batch)        # [b, G2, K]
    prob = jax.nn.sigmoid(heat_logits)
    is_center = (targets >= 1.0 - 1e-6).astype(jnp.float32)
    log_p = jax.nn.log_sigmoid(heat_logits)
    log_np = jax.nn.log_sigmoid(-heat_logits)
    pos_loss = -((1.0 - prob) ** p.focal_alpha) * log_p * is_center
    neg_loss = -((1.0 - targets) ** p.focal_beta) * (prob ** p.focal_alpha) \
        * log_np * (1.0 - is_center)
    num_pos = jnp.maximum(jnp.sum(is_center), 1.0)
    heat_loss = (jnp.sum(pos_loss) + jnp.sum(neg_loss)) / num_pos

    diff = (predictions.box_residuals.astype(jnp.float32)
            - input_batch.reg_targets)
    huber = jnp.where(jnp.abs(diff) < 1.0, 0.5 * diff * diff,
                      jnp.abs(diff) - 0.5)
    w = input_batch.reg_weights
    reg_loss = jnp.sum(huber.sum(-1) * w) / jnp.maximum(jnp.sum(w), 1.0)

    total = heat_loss + p.reg_loss_weight * reg_loss
    b = float(heat_logits.shape[0])
    metrics = NestedMap(loss=(total, b), heat_loss=(heat_loss, b),
                        reg_loss=(reg_loss, b))
    if p.centerness_loss_weight > 0:
      cent_t = jnp.max(targets, axis=-1)                # [b, G2]
      cent_logits = predictions.centerness_logits.astype(jnp.float32)
      cent_loss = jnp.mean(
          cent_t * -jax.nn.log_sigmoid(cent_logits)
          + (1.0 - cent_t) * -jax.nn.log_sigmoid(-cent_logits))
      total = total + p.centerness_loss_weight * cent_loss
      metrics.loss = (total, b)
      metrics.centerness_loss = (cent_loss, b)
    return metrics, NestedMap()

  def Decode(self, theta, input_batch):
    p = self.p
    g = p.backbone.grid_size
    preds = self.ComputePredictions(theta, input_batch)
    heat = jax.nn.sigmoid(preds.heat_logits.astype(jnp.float32))
    if p.centerness_loss_weight > 0:
      heat = heat * jax.nn.sigmoid(
          preds.centerness_logits.astype(jnp.float32))[..., None]
    b, g2, k = heat.shape
    peaks = HeatMapPeaks(heat.reshape(b, g, g, k),
                         p.peak_kernel_size).reshape(b, g2, k)
    cell_score = jnp.max(peaks, -1)                     # [b, G2]
    cell_cls = jnp.argmax(peaks, -1) + 1
    topk = p.num_boxes_to_decode
    top_scores, top_cells = jax.lax.top_k(cell_score, topk)
    top_boxes = jnp.take_along_axis(preds.box_residuals,
                                    top_cells[..., None], axis=1)
    top_cls = jnp.take_along_axis(cell_cls, top_cells, axis=1)
    return NestedMap(scores=top_scores, cells=top_cells, boxes=top_boxes,
                     classes=top_cls,
                     gt_cls_targets=input_batch.cls_targets,
                     gt_reg_targets=input_batch.reg_targets,
                     gt_reg_weights=input_batch.reg_weights)
