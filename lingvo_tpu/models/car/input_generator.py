"""Car input: synthetic pillared point clouds with box targets (ref the
KITTI/Waymo loaders in `lingvo/tasks/car/` — here the target-assignment
convention those pipelines produce, generated synthetically)."""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class SyntheticCarInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("grid_size", 16, "BEV grid G (world is [0, G) x [0, G)).")
    p.Define("max_pillars", 64, "P.")
    p.Define("points_per_pillar", 8, "N.")
    p.Define("num_objects", 3, "Ground-truth boxes per scene.")
    p.Define("num_classes", 2, "Foreground classes.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  @property
  def point_dim(self):
    return 4  # x, y, z, intensity

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 48271 * self._step) % (2**31))
    self._step += 1
    b, g = p.batch_size, p.grid_size
    pts = np.zeros((b, p.max_pillars, p.points_per_pillar, 4), np.float32)
    ppad = np.ones((b, p.max_pillars, p.points_per_pillar), np.float32)
    cells = np.full((b, p.max_pillars), -1, np.int32)
    cls_t = np.zeros((b, g * g), np.int32)
    reg_t = np.zeros((b, g * g, 7), np.float32)
    reg_w = np.zeros((b, g * g), np.float32)
    boxes = [[] for _ in range(b)]
    for i in range(b):
      pillar = 0
      for _ in range(p.num_objects):
        cx, cy = rng.uniform(1, g - 1, 2)
        cz = rng.uniform(-1, 1)
        l, w, h = rng.uniform(0.5, 2.0, 3)
        theta = rng.uniform(-np.pi, np.pi)
        cls = rng.randint(1, p.num_classes + 1)
        boxes[i].append((np.array([cx, cy, cz, l, w, h, theta], np.float32),
                         cls))
        cell = int(cy) * g + int(cx)
        cls_t[i, cell] = cls
        # residuals relative to the cell center (standard encoding)
        reg_t[i, cell] = [cx - (int(cx) + 0.5), cy - (int(cy) + 0.5),
                          cz, l, w, h, theta]
        reg_w[i, cell] = 1.0
        # a couple of pillars of points inside the box
        for _ in range(2):
          if pillar >= p.max_pillars:
            break
          n = rng.randint(2, p.points_per_pillar + 1)
          pts[i, pillar, :n, 0] = cx + rng.uniform(-l / 2, l / 2, n)
          pts[i, pillar, :n, 1] = cy + rng.uniform(-w / 2, w / 2, n)
          pts[i, pillar, :n, 2] = cz + rng.uniform(-h / 2, h / 2, n)
          pts[i, pillar, :n, 3] = cls  # class-colored intensity: learnable
          ppad[i, pillar, :n] = 0.0
          px = int(np.clip(pts[i, pillar, 0, 0], 0, g - 1))
          py = int(np.clip(pts[i, pillar, 0, 1], 0, g - 1))
          cells[i, pillar] = py * g + px
          pillar += 1
    # Flat "laser" view + ground-truth boxes (what point-based detectors
    # like StarNet consume; the pillar view above serves PointPillars).
    m = p.max_pillars * p.points_per_pillar
    lasers = pts.reshape(b, m, 4)
    laser_paddings = ppad.reshape(b, m)
    gt_boxes = np.zeros((b, p.num_objects, 7), np.float32)
    gt_classes = np.zeros((b, p.num_objects), np.int32)
    for i in range(b):
      for j, (box, cls) in enumerate(boxes[i][:p.num_objects]):
        gt_boxes[i, j] = box
        gt_classes[i, j] = cls
    return NestedMap(
        pillar_points=pts, point_paddings=ppad, pillar_cells=cells,
        cls_targets=cls_t, reg_targets=reg_t, reg_weights=reg_w,
        lasers=lasers, laser_paddings=laser_paddings,
        gt_boxes=gt_boxes, gt_classes=gt_classes)
