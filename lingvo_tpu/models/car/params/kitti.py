"""Car configs (ref `lingvo/tasks/car/params/kitti.py` StarNetCarModel /
PointPillars recipes): synthetic-scene smoke configs plus the KITTI-format
file-based recipe over the native yielder."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.car import input_generator
from lingvo_tpu.models.car import pillars


@model_registry.RegisterSingleTaskModel
class PointPillarsCar(base_model_params.SingleTaskModelParams):

  BATCH_SIZE = 16
  GRID = 16
  FEATURE_DIM = 64

  def Train(self):
    return input_generator.SyntheticCarInput.Params().Set(
        batch_size=self.BATCH_SIZE, grid_size=self.GRID)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    p = pillars.PointPillarsModel.Params()
    p.name = "car_pillars"
    p.featurizer.point_dim = 4
    p.featurizer.feature_dim = self.FEATURE_DIM
    p.backbone.grid_size = self.GRID
    p.backbone.feature_dim = self.FEATURE_DIM
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.Constant.Params())
    p.train.tpu_steps_per_loop = 50
    return p


@model_registry.RegisterSingleTaskModel
class StarNetCar(base_model_params.SingleTaskModelParams):
  """StarNet point-based detector (ref `kitti.py` StarNetCarModel0701)."""

  BATCH_SIZE = 8

  def Train(self):
    return input_generator.SyntheticCarInput.Params().Set(
        batch_size=self.BATCH_SIZE)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    from lingvo_tpu.models.car import starnet
    p = starnet.StarNetModel.Params()
    p.name = "starnet_car"
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.Constant.Params())
    p.train.tpu_steps_per_loop = 50
    return p


@model_registry.RegisterSingleTaskModel
class StarNetCarKitti(StarNetCar):
  """StarNet on KITTI-format scene files (ref StarNetCarModel0701 +
  kitti_input_generator.py). Point KITTI_SCENES at JSONL scene records
  produced by tools (see models/car/kitti_input.py record format)."""

  KITTI_SCENES = "text:/data/kitti/train_scenes.jsonl-*"
  KITTI_TEST_SCENES = "text:/data/kitti/val_scenes.jsonl-*"
  NUM_CLASSES = 3  # Car / Pedestrian / Cyclist

  def Train(self):
    from lingvo_tpu.models.car import kitti_input
    return kitti_input.KittiSceneInputGenerator.Params().Set(
        batch_size=self.BATCH_SIZE, file_pattern=self.KITTI_SCENES,
        num_classes=self.NUM_CLASSES, max_points=1024, max_objects=32,
        grid_size=64, grid_range_x=(0.0, 70.4), grid_range_y=(-40.0, 40.0))

  def Test(self):
    return self.Train().Set(file_pattern=self.KITTI_TEST_SCENES,
                            shuffle=False, max_epochs=1)

  def Task(self):
    p = super().Task()
    p.num_classes = self.NUM_CLASSES
    p.num_centers = 128
    p.use_oriented_nms = True
    p.max_detections = 32
    return p


@model_registry.RegisterSingleTaskModel
class StarNetCarTiny(StarNetCar):
  """CPU-smoke scale."""

  BATCH_SIZE = 2

  def Train(self):
    return super().Train().Set(max_pillars=16, points_per_pillar=4,
                               num_objects=2)

  def Task(self):
    p = super().Task()
    p.num_centers = 8
    p.featurizer.num_neighbors = 8
    p.featurizer.mlp_dims = (16, 16)
    p.hidden_dim = 16
    p.max_detections = 4
    p.train.max_steps = 60
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class AnchorFreePillarsCar(PointPillarsCar):
  """Anchor-free (CenterNet-style) pillars detector (ref
  `pillars_anchor_free.py` ModelV2 recipe on the pillars backbone)."""

  def Task(self):
    base = super().Task()
    p = pillars.AnchorFreePillarsModel.Params()
    for name in ("featurizer", "backbone", "train"):
      p.Set(**{name: base.Get(name)})
    p.name = "car_pillars_anchor_free"
    return p
