"""Waymo Open Dataset configs (ref `lingvo/tasks/car/params/waymo.py`
StarNetVehicle / PointPillars recipes): PointPillars-at-scale over the
Waymo-format file input on the native yielder (VERDICT r3 Missing #4)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.car import pillars
from lingvo_tpu.models.car import waymo_input


@model_registry.RegisterSingleTaskModel
class PointPillarsWaymoVehicle(base_model_params.SingleTaskModelParams):
  """PointPillars on Waymo vehicles (ref waymo.py PointPillars configs:
  [-76.8, 76.8] range, vehicle class only)."""

  WAYMO_FRAMES = "text:/data/waymo/train_frames.jsonl-*"
  WAYMO_TEST_FRAMES = "text:/data/waymo/val_frames.jsonl-*"
  BATCH_SIZE = 8
  GRID = 128
  MAX_POINTS = 32768
  MAX_PILLARS = 4096
  POINTS_PER_PILLAR = 32
  FEATURE_DIM = 64
  NUM_CLASSES = 1  # vehicles

  def _Input(self, pattern):
    return waymo_input.WaymoSceneInputGenerator.Params().Set(
        batch_size=self.BATCH_SIZE, file_pattern=pattern,
        num_classes=self.NUM_CLASSES, max_points=self.MAX_POINTS,
        max_objects=64, grid_size=self.GRID,
        grid_range_x=(-76.8, 76.8), grid_range_y=(-76.8, 76.8),
        max_pillars=self.MAX_PILLARS,
        points_per_pillar=self.POINTS_PER_PILLAR)

  def Train(self):
    return self._Input(self.WAYMO_FRAMES)

  def Test(self):
    return self._Input(self.WAYMO_TEST_FRAMES).Set(
        shuffle=False, max_epochs=1)

  # subclasses swap the detector while inheriting the full recipe
  TASK_CLASS = pillars.PointPillarsModel
  TASK_NAME = "pillars_waymo_vehicle"

  def Task(self):
    p = self.TASK_CLASS.Params()
    p.name = self.TASK_NAME
    p.featurizer.point_dim = waymo_input.POINT_DIM  # + intensity/elongation
    p.featurizer.feature_dim = self.FEATURE_DIM
    p.backbone.grid_size = self.GRID
    p.backbone.feature_dim = self.FEATURE_DIM
    p.backbone.num_classes = self.NUM_CLASSES  # foreground; bg is internal
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=2e-4,
        optimizer=opt_lib.Adam.Params(),
        lr_schedule=sched_lib.LinearRampupCosineDecay.Params().Set(
            warmup_steps=1000, total_steps=75000),
        clip_gradient_norm_to_value=5.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class PointPillarsWaymoTiny(PointPillarsWaymoVehicle):
  """CPU-smoke scale over tiny Waymo-format fixture files."""

  WAYMO_FRAMES = "text:/tmp/waymo/train_frames.jsonl"
  WAYMO_TEST_FRAMES = "text:/tmp/waymo/train_frames.jsonl"
  BATCH_SIZE = 2
  GRID = 16
  MAX_POINTS = 256
  MAX_PILLARS = 64
  POINTS_PER_PILLAR = 8
  FEATURE_DIM = 16

  def _Input(self, pattern):
    return super()._Input(pattern).Set(
        max_objects=8, grid_range_x=(-16.0, 16.0),
        grid_range_y=(-16.0, 16.0))

  def Task(self):
    p = super().Task()
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.learner.learning_rate = 1e-3
    p.train.max_steps = 60
    p.train.tpu_steps_per_loop = 20
    return p


class _DeepFusionMixin:
  """Swaps the detector to DeepFusionModel and wires the camera stream;
  composes with any PointPillarsWaymo* recipe (DeepFusionModel.Params
  extends PointPillarsModel.Params, so the inherited Task() config applies
  unchanged)."""

  TASK_NAME = "deep_fusion_waymo_vehicle"
  CAMERA_SIZE = 192
  IMAGE_CHANNELS = 64
  ATTEN_DROPOUT = 0.3  # ref LearnableAlign keep_prob 0.7

  @property
  def TASK_CLASS(self):
    from lingvo_tpu.models.car import deep_fusion
    return deep_fusion.DeepFusionModel

  def _Input(self, pattern):
    return super()._Input(pattern).Set(camera_size=self.CAMERA_SIZE)

  def Task(self):
    p = super().Task()
    p.camera_featurizer.image_channels = self.IMAGE_CHANNELS
    p.aligner.lidar_channels = self.FEATURE_DIM
    p.aligner.image_channels = self.IMAGE_CHANNELS
    p.aligner.qkv_channels = self.FEATURE_DIM
    p.aligner.atten_dropout_prob = self.ATTEN_DROPOUT
    return p


@model_registry.RegisterSingleTaskModel
class DeepFusionWaymoVehicle(_DeepFusionMixin, PointPillarsWaymoVehicle):
  """Camera+lidar fusion detector (ref `deep_fusion.py`,
  arXiv:2203.08195): PointPillars with LearnableAlign cross-attention
  over camera patch tokens."""


@model_registry.RegisterSingleTaskModel
class DeepFusionWaymoTiny(_DeepFusionMixin, PointPillarsWaymoTiny):
  """CPU-smoke scale: the tiny pillars recipe + fusion."""

  CAMERA_SIZE = 32
  IMAGE_CHANNELS = 16
  ATTEN_DROPOUT = 0.0

  def Task(self):
    p = super().Task()
    p.camera_featurizer.filter_counts = [8, 16]
    return p
