"""Waymo Open Dataset-format input over the native record yielder.

Re-designs `lingvo/tasks/car/waymo/waymo_open_input_generator.py` (frame
metadata + multi-laser extraction + label extraction with speed and
difficulty) for the TPU-native pipeline: records flow through the C++
shuffle-ring yielder as JSON-line frames instead of TFRecords of waymo
protos, and featurization happens host-side in numpy with on-device target
assignment downstream (same split as the KITTI path).

Record format (one JSON object per line):
  {"lasers": {"TOP": [[x, y, z, intensity, elongation], ...], ...}
     or "points": [[x, y, z, intensity, elongation], ...],
   "labels": [{"box": [cx, cy, cz, l, w, h, heading],   # vehicle frame
               "type": "TYPE_VEHICLE" | 1,
               "num_points": 17,            # optional
               "difficulty": 1 | 2,          # optional (derived if absent)
               "speed": [vx, vy],            # optional
               "accel": [ax, ay]}, ...],
   "pose": [16 floats],                      # optional world<-SDC 4x4
   "run_segment": "...", "time_of_day": "Day", "weather": "sunny"}

Waymo gives 7-DOF boxes directly in the vehicle frame (no camera->velo
conversion) and 2 extra per-point features (intensity, elongation) vs
KITTI's reflectance — point_dim is 5.
"""

from __future__ import annotations

import json

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap

# ref waymo_open_dataset label.proto Type enum
WAYMO_CLASS_IDS = {
    "TYPE_VEHICLE": 1,
    "TYPE_PEDESTRIAN": 2,
    "TYPE_SIGN": 3,
    "TYPE_CYCLIST": 4,
}
POINT_DIM = 5  # x, y, z, intensity, elongation

# ref waymo difficulty: boxes with <= 5 points are LEVEL_2
LEVEL_2_MAX_POINTS = 5


def ParseWaymoLabel(obj: dict, keep_classes: int):
  """Label dict -> (box7, class_id, num_points, difficulty, speed2) or
  None for out-of-split / malformed labels."""
  box = np.asarray(obj.get("box", ()), np.float32).reshape(-1)
  if box.shape != (7,):
    return None
  cls = obj.get("type", 0)
  if isinstance(cls, str):
    cls = WAYMO_CLASS_IDS.get(cls, 0)
  cls = int(cls)
  if not 0 < cls <= keep_classes:
    return None
  num_points = int(obj.get("num_points", 0))
  difficulty = obj.get("difficulty")
  if difficulty is None:
    difficulty = 2 if num_points <= LEVEL_2_MAX_POINTS else 1
  speed = np.zeros((2,), np.float32)
  if obj.get("speed") is not None:
    sp = np.asarray(obj["speed"], np.float32).reshape(-1)[:2]
    speed[:len(sp)] = sp
  return box, cls, num_points, int(difficulty), speed


class WaymoSceneInputGenerator(
    base_input_generator.FileBasedSequenceInputGenerator):
  """JSON-line Waymo frames -> fixed-shape detection batches.

  Emits the KITTI-path fields (pillar/grid views + gt boxes/classes) plus
  Waymo extras: gt_difficulty, gt_num_points, gt_speed — what the
  per-difficulty/per-range breakdown metrics slice on (ref
  waymo_open_input_generator.WaymoLaserExtractor + label extraction).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("max_points", 4096, "Lasers padded/subsampled to this count.")
    p.Define("max_objects", 64, "GT boxes padded to this count.")
    p.Define("grid_size", 64, "BEV grid cells per axis.")
    p.Define("grid_range_x", (-76.8, 76.8),
             "(min, max) world x covered by the grid (ref waymo.py "
             "pointpillars ranges).")
    p.Define("grid_range_y", (-76.8, 76.8), "(min, max) world y.")
    p.Define("max_pillars", 512, "P.")
    p.Define("points_per_pillar", 16, "N.")
    p.Define("num_classes", 4,
             "Foreground classes kept in WAYMO_CLASS_IDS order "
             "(1 keeps only vehicles).")
    p.Define("camera_size", 0,
             "If >0, emit a `camera` [S, S, 3] image per frame (records "
             "carry \"camera\" as a flat or nested float list; frames "
             "without one — or with a different resolution — get zeros) "
             "— the DeepFusion input (ref deep_fusion.py "
             "MultiModalFeaturizer camera_names).")
    p.Define("augmentors", [],
             "List of augmentation.Augmentor Params applied per frame "
             "(points + gt boxes) before view assembly. Configure on the "
             "Train() dataset only (ref input_preprocessors.py train-time "
             "preprocessor lists).")
    p.bucket_upper_bound = [1]
    return p

  def __init__(self, params):
    params = params.Copy()
    params.bucket_upper_bound = [1]
    params.bucket_batch_limit = [params.batch_size or 2]
    super().__init__(params)
    self._record_counter = 0
    from lingvo_tpu.models.car import augmentation
    self._augmentors = augmentation.BuildPipeline(self.p.augmentors)

  def ProcessRecord(self, record: bytes):
    p = self.p
    self._record_counter += 1
    try:
      frame = json.loads(record.decode("utf-8"))
      if not isinstance(frame, dict):
        return None
      if "lasers" in frame:
        clouds = [np.asarray(v, np.float32).reshape(-1, POINT_DIM)
                  for v in frame["lasers"].values()]
        pts = (np.concatenate(clouds, axis=0) if clouds
               else np.zeros((0, POINT_DIM), np.float32))
      else:
        pts = np.asarray(frame.get("points", []),
                         np.float32).reshape(-1, POINT_DIM)
      labels = [ParseWaymoLabel(o, p.num_classes)
                for o in frame.get("labels", [])]
      camera = None
      if p.camera_size > 0:
        s = p.camera_size
        camera = np.zeros((s, s, 3), np.float32)
        if frame.get("camera") is not None:
          raw = np.asarray(frame["camera"], np.float32)
          if raw.size == s * s * 3:
            camera = raw.reshape(s, s, 3)
          # wrong-resolution cameras degrade to zeros: the frame's lidar
          # and labels are still good training data, and a reshape error
          # here would alias into the malformed-frame drop path
    except (UnicodeDecodeError, json.JSONDecodeError, ValueError,
            TypeError, AttributeError):
      return None  # malformed frame: drop, never kill the pipeline
    labels = [l for l in labels if l is not None]

    if self._augmentors:
      from lingvo_tpu.models.car import augmentation
      scene_nm = augmentation.MakeScene(
          pts, np.asarray([l[0] for l in labels],
                          np.float32).reshape(-1, 7),
          [l[1] for l in labels])
      scene_nm.difficulty = np.asarray([l[3] for l in labels], np.int32)
      scene_nm.box_extras = {
          "num_points": np.asarray([l[2] for l in labels], np.int32),
          "speed": np.asarray([l[4] for l in labels],
                              np.float32).reshape(-1, 2),
      }
      scene_nm = augmentation.ApplyPipeline(
          self._augmentors, scene_nm, seed=self._record_counter)
      pts = scene_nm.points
      labels = [
          (scene_nm.boxes[i], int(scene_nm.classes[i]),
           int(scene_nm.box_extras["num_points"][i]),
           int(scene_nm.difficulty[i]), scene_nm.box_extras["speed"][i])
          for i in range(scene_nm.boxes.shape[0])]

    from lingvo_tpu.models.car import detection_3d
    (lasers,), lpad = detection_3d.RandomPadOrTrimTo(
        [pts], p.max_points,
        key=self._record_counter * 2654435761 + len(pts))

    gt_boxes = np.zeros((p.max_objects, 7), np.float32)
    gt_classes = np.zeros((p.max_objects,), np.int32)
    gt_difficulty = np.zeros((p.max_objects,), np.int32)
    gt_num_points = np.zeros((p.max_objects,), np.int32)
    gt_speed = np.zeros((p.max_objects, 2), np.float32)
    boxes, classes = [], []
    for i, (box, cls, npts, diff, speed) in enumerate(labels):
      if i >= p.max_objects:
        break
      gt_boxes[i] = box
      gt_classes[i] = cls
      gt_difficulty[i] = diff
      gt_num_points[i] = npts
      gt_speed[i] = speed
      boxes.append(box)
      classes.append(cls)

    views = detection_3d.SceneToDetectionViews(
        lasers, lpad, boxes, classes,
        grid_size=p.grid_size, grid_range_x=p.grid_range_x,
        grid_range_y=p.grid_range_y, max_pillars=p.max_pillars,
        points_per_pillar=p.points_per_pillar)
    views.update(
        bucket_key=1,
        lasers=lasers, laser_paddings=lpad,
        gt_boxes=gt_boxes, gt_classes=gt_classes,
        gt_difficulty=gt_difficulty, gt_num_points=gt_num_points,
        gt_speed=gt_speed)
    if camera is not None:
      views.camera = camera
    return views
