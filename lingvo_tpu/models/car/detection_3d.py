"""3D detection utilities: anchors, assignment, residual box coding, rotated
IoU, oriented NMS (ref `lingvo/tasks/car/detection_3d_lib.py` Utils3D and
`detection_decoder.py` DecodeWithNMS).

TPU-native design notes:
  * Everything is jax and jit-able with STATIC shapes — assignment and NMS
    run on device inside the train/decode step (the reference's rotated IoU
    and oriented NMS are C++ CPU ops, `ops.non_max_suppression_3d`).
  * Rotated IoU is exact: Sutherland–Hodgman polygon clipping with a
    fixed-size vertex buffer (a convex quad clipped by 4 half-planes has at
    most 8 vertices; buffer 16), prefix-compacted after every clip so the
    whole thing vmaps over anchor x gt pairs.
  * Oriented NMS is a lax.fori_loop greedy argmax-and-suppress over a
    precomputed [N, N] rotated-IoU matrix.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core.nested_map import NestedMap

_MAX_VERTS = 16


# ---------------------------------------------------------------------------
# Geometry: corners, rotated IoU
# ---------------------------------------------------------------------------


def BoxCorners2D(boxes):
  """[..., 5] (x, y, dx, dy, phi) -> [..., 4, 2] CCW corners."""
  x, y, dx, dy, phi = [boxes[..., i] for i in range(5)]
  hx, hy = dx / 2.0, dy / 2.0
  base = jnp.stack([
      jnp.stack([hx, hy], -1),
      jnp.stack([-hx, hy], -1),
      jnp.stack([-hx, -hy], -1),
      jnp.stack([hx, -hy], -1),
  ], axis=-2)                                            # [..., 4, 2]
  c, s = jnp.cos(phi), jnp.sin(phi)
  rot = jnp.stack([jnp.stack([c, -s], -1), jnp.stack([s, c], -1)], -2)
  return jnp.einsum("...vj,...ij->...vi", base, rot) + jnp.stack(
      [x, y], -1)[..., None, :]


def BBoxCorners3D(bboxes):
  """[..., 7] (x,y,z,dx,dy,dz,phi) -> [..., 8, 3] corners (ref
  geometry.BBoxCorners)."""
  bev = BoxCorners2D(jnp.concatenate(
      [bboxes[..., 0:2], bboxes[..., 3:5], bboxes[..., 6:7]], -1))
  z, dz = bboxes[..., 2], bboxes[..., 5]
  lo = (z - dz / 2.0)[..., None, None]
  hi = (z + dz / 2.0)[..., None, None]
  bot = jnp.concatenate([bev, jnp.broadcast_to(lo, bev[..., :1].shape)], -1)
  top = jnp.concatenate([bev, jnp.broadcast_to(hi, bev[..., :1].shape)], -1)
  return jnp.concatenate([bot, top], axis=-2)


def _ClipHalfPlane(verts, n, a, b):
  """Clips a prefix-compact polygon by the half-plane LEFT of edge a->b.

  verts [M, 2], n scalar int (valid prefix length). Returns (verts', n').
  """
  m = verts.shape[0]
  idx = jnp.arange(m)
  nxt_idx = jnp.where(idx + 1 < n, idx + 1, 0)
  cur = verts
  nxt = verts[nxt_idx]

  def _Side(p):
    return ((b[0] - a[0]) * (p[..., 1] - a[1]) -
            (b[1] - a[1]) * (p[..., 0] - a[0]))

  d_cur, d_nxt = _Side(cur), _Side(nxt)
  cur_in = d_cur >= 0
  nxt_in = d_nxt >= 0
  denom = d_cur - d_nxt
  t = d_cur / jnp.where(jnp.abs(denom) < 1e-12, 1.0, denom)
  inter = cur + t[:, None] * (nxt - cur)

  live = idx < n
  e1 = cur_in & live                       # emit current vertex
  e2 = (cur_in ^ nxt_in) & live            # emit edge intersection
  counts = e1.astype(jnp.int32) + e2.astype(jnp.int32)
  start = jnp.cumsum(counts) - counts
  pos1 = jnp.where(e1, start, m)           # m -> dropped
  pos2 = jnp.where(e2, start + e1.astype(jnp.int32), m)
  out = jnp.zeros_like(verts)
  out = out.at[pos1].set(cur, mode="drop")
  out = out.at[pos2].set(inter, mode="drop")
  return out, jnp.sum(counts)


def _PolyArea(verts, n):
  """Shoelace area of a prefix-compact polygon."""
  m = verts.shape[0]
  idx = jnp.arange(m)
  nxt = verts[jnp.where(idx + 1 < n, idx + 1, 0)]
  cross = verts[:, 0] * nxt[:, 1] - verts[:, 1] * nxt[:, 0]
  return 0.5 * jnp.abs(jnp.sum(jnp.where(idx < n, cross, 0.0)))


def _PairIntersectionArea(corners_a, corners_b):
  """Intersection area of two CCW quads [4, 2] x [4, 2]."""
  verts = jnp.zeros((_MAX_VERTS, 2), corners_a.dtype).at[:4].set(corners_a)
  n = jnp.asarray(4, jnp.int32)
  for i in range(4):
    verts, n = _ClipHalfPlane(verts, n, corners_b[i], corners_b[(i + 1) % 4])
  return _PolyArea(verts, n)


def RotatedIou2D(boxes_a, boxes_b):
  """Exact BEV rotated IoU. boxes [N, 5] / [M, 5] (x, y, dx, dy, phi) ->
  [N, M] (ref geometry rotated-IoU C++ op)."""
  ca = BoxCorners2D(boxes_a)                             # [N, 4, 2]
  cb = BoxCorners2D(boxes_b)                             # [M, 4, 2]
  inter = jax.vmap(lambda a: jax.vmap(
      lambda b: _PairIntersectionArea(a, b))(cb))(ca)    # [N, M]
  area_a = (boxes_a[:, 2] * boxes_a[:, 3])[:, None]
  area_b = (boxes_b[:, 2] * boxes_b[:, 3])[None, :]
  union = jnp.maximum(area_a + area_b - inter, 1e-9)
  return inter / union


def _Bev(bboxes7):
  return jnp.concatenate(
      [bboxes7[..., 0:2], bboxes7[..., 3:5], bboxes7[..., 6:7]], -1)


def RotatedIou7DOF(bboxes_a, bboxes_b):
  """[N, 7] x [M, 7] -> [N, M] BEV IoU ignoring z (ref
  IOU2DRotatedBoxes:234 `_IgnoreZCoordinate`)."""
  return RotatedIou2D(_Bev(bboxes_a), _Bev(bboxes_b))


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------


def ScaledHuberLoss(labels, predictions, weights=1.0, delta=1.0):
  """Huber loss scaled by 1/delta (ref Utils3D.ScaledHuberLoss:57 — equals
  sigma^2-parameterized SmoothL1 with sigma^2 = 1/delta)."""
  err = predictions - labels
  abs_err = jnp.abs(err)
  quad = jnp.minimum(abs_err, delta)
  lin = abs_err - quad
  return (0.5 * quad * quad + delta * lin) * weights / delta


def CornerLoss(gt_bboxes, predicted_bboxes, symmetric=True):
  """Summed Huber loss over the 8 box corners [..., 7] -> [...] (ref
  CornerLoss:93; `symmetric` takes the min vs the 180-degree-flipped gt)."""
  gt_c = BBoxCorners3D(gt_bboxes)
  pr_c = BBoxCorners3D(predicted_bboxes)
  loss = jnp.sum(ScaledHuberLoss(gt_c, pr_c), axis=(-2, -1))
  if symmetric:
    rot = jnp.zeros_like(gt_bboxes).at[..., 6].set(math.pi)
    gt_rot = BBoxCorners3D(gt_bboxes + rot)
    loss_rot = jnp.sum(ScaledHuberLoss(gt_rot, pr_c), axis=(-2, -1))
    loss = jnp.minimum(loss, loss_rot)
  return loss


# ---------------------------------------------------------------------------
# Anchors
# ---------------------------------------------------------------------------


def CreateDenseCoordinates(ranges, center_in_cell=False):
  """[(min, max, num), ...] -> [prod(num), len(ranges)] dense grid (ref
  CreateDenseCoordinates:144)."""
  axes = []
  for lo, hi, num in ranges:
    num = int(num)
    if center_in_cell:
      step = (hi - lo) / num
      axes.append(lo + step * (jnp.arange(num) + 0.5))
    else:
      axes.append(jnp.linspace(lo, hi, num))
  grids = jnp.meshgrid(*axes, indexing="ij")
  return jnp.stack([g.reshape(-1) for g in grids], axis=-1)


def MakeAnchorBoxes(anchor_centers, anchor_box_dimensions,
                    anchor_box_rotations, anchor_box_offsets=None):
  """centers [N, 3] x dims [D, 3] x rotations [R] (+offsets [D, 3]) ->
  [N * D * R, 7] anchors (ref MakeAnchorBoxes:185)."""
  n = anchor_centers.shape[0]
  dims = jnp.asarray(anchor_box_dimensions, jnp.float32)       # [D, 3]
  rots = jnp.asarray(anchor_box_rotations, jnp.float32)        # [R]
  d, r = dims.shape[0], rots.shape[0]
  offsets = (jnp.asarray(anchor_box_offsets, jnp.float32)
             if anchor_box_offsets is not None else jnp.zeros((d, 3)))
  centers = anchor_centers[:, None, None, :] + offsets[None, :, None, :]
  centers = jnp.broadcast_to(centers, (n, d, r, 3))
  dims_b = jnp.broadcast_to(dims[None, :, None, :], (n, d, r, 3))
  rots_b = jnp.broadcast_to(rots[None, None, :, None], (n, d, r, 1))
  return jnp.concatenate([centers, dims_b, rots_b], -1).reshape(-1, 7)


# ---------------------------------------------------------------------------
# Assignment + residual coding
# ---------------------------------------------------------------------------


def AssignAnchors(anchor_bboxes, gt_bboxes, gt_bboxes_labels, gt_bboxes_mask,
                  foreground_assignment_threshold=0.5,
                  background_assignment_threshold=0.35,
                  background_class_id=0, force_match=True,
                  similarity_fn=None):
  """SSD-style anchor assignment (ref AssignAnchors:262).

  anchor_bboxes [A, 7]; gt_bboxes [G, 7]; gt_bboxes_labels [G] int;
  gt_bboxes_mask [G] (1 = real). Returns NestedMap with assigned_gt_bbox
  [A, 7], assigned_gt_labels [A], assigned_gt_idx [A], assigned_cls_mask [A]
  (1 for foreground AND background; 0 for ignored), assigned_reg_mask [A]
  (1 for foreground only).
  """
  similarity_fn = similarity_fn or RotatedIou7DOF
  sim = similarity_fn(anchor_bboxes, gt_bboxes)          # [A, G]
  sim = sim * gt_bboxes_mask[None, :].astype(sim.dtype)
  best_score = jnp.max(sim, axis=1)                      # [A]
  best_idx = jnp.argmax(sim, axis=1)                     # [A]

  fg = best_score >= foreground_assignment_threshold
  bg = best_score <= background_assignment_threshold

  if force_match:
    # each real gt's best anchor becomes foreground when its score > 0
    a = anchor_bboxes.shape[0]
    best_anchor = jnp.argmax(sim, axis=0)                # [G]
    gt_best_score = jnp.max(sim, axis=0)                 # [G]
    forced = (gt_bboxes_mask > 0) & (gt_best_score > 0)
    g_idx = jnp.arange(gt_bboxes.shape[0])
    scatter_to = jnp.where(forced, best_anchor, a)       # a -> dropped
    force_mask = jnp.zeros((a,), jnp.bool_).at[scatter_to].set(
        True, mode="drop")
    forced_gt = jnp.full((a,), 0, jnp.int32).at[scatter_to].set(
        g_idx.astype(jnp.int32), mode="drop")
    best_idx = jnp.where(force_mask, forced_gt, best_idx)
    fg = fg | force_mask
    bg = bg & ~force_mask

  assigned_gt_bbox = gt_bboxes[best_idx]
  labels = gt_bboxes_labels[best_idx]
  assigned_gt_labels = jnp.where(fg, labels, background_class_id)
  cls_mask = (fg | bg).astype(jnp.float32)
  reg_mask = fg.astype(jnp.float32)
  return NestedMap(
      assigned_gt_bbox=assigned_gt_bbox,
      assigned_gt_idx=best_idx.astype(jnp.int32),
      assigned_gt_labels=assigned_gt_labels.astype(jnp.int32),
      assigned_gt_similarity_score=best_score,
      assigned_cls_mask=cls_mask,
      assigned_reg_mask=reg_mask)


def LocalizationResiduals(anchor_bboxes, assigned_gt_bboxes):
  """[..., 7] anchors + assigned gts -> [..., 7] target residuals (ref
  LocalizationResiduals:453; VoxelNet diagonal normalization, log dims)."""
  xa, ya, za, dxa, dya, dza, pa = [anchor_bboxes[..., i] for i in range(7)]
  xg, yg, zg, dxg, dyg, dzg, pg = [
      assigned_gt_bboxes[..., i] for i in range(7)]
  diag = jnp.sqrt(dxa * dxa + dya * dya)
  return jnp.stack([
      (xg - xa) / diag,
      (yg - ya) / diag,
      (zg - za) / dza,
      jnp.log(dxg / dxa),
      jnp.log(dyg / dya),
      jnp.log(dzg / dza),
      pg - pa,
  ], axis=-1)


def ResidualsToBBoxes(anchor_bboxes, residuals,
                      min_angle_rad=-math.pi, max_angle_rad=math.pi):
  """Inverse of LocalizationResiduals (ref ResidualsToBBoxes:540); the
  predicted angle is wrapped into [min_angle_rad, max_angle_rad)."""
  xa, ya, za, dxa, dya, dza, pa = [anchor_bboxes[..., i] for i in range(7)]
  rx, ry, rz, rdx, rdy, rdz, rp = [residuals[..., i] for i in range(7)]
  diag = jnp.sqrt(dxa * dxa + dya * dya)
  phi = pa + rp
  span = max_angle_rad - min_angle_rad
  phi = jnp.where(span > 0,
                  jnp.mod(phi - min_angle_rad, span) + min_angle_rad, phi)
  return jnp.stack([
      xa + rx * diag,
      ya + ry * diag,
      za + rz * dza,
      dxa * jnp.exp(rdx),
      dya * jnp.exp(rdy),
      dza * jnp.exp(rdz),
      phi,
  ], axis=-1)


# ---------------------------------------------------------------------------
# Oriented NMS + decode
# ---------------------------------------------------------------------------


def OrientedNMSIndices(bboxes, scores, max_output_size,
                       nms_iou_threshold=0.3, score_threshold=0.01):
  """Greedy rotated-IoU NMS (ref BatchedOrientedNMSIndices:719 /
  the C++ non_max_suppression_3d kernel).

  bboxes [N, 7], scores [N] -> (indices [max_output_size] int32,
  mask [max_output_size] 1/0).
  """
  iou = RotatedIou7DOF(bboxes, bboxes)                   # [N, N]
  neg_inf = jnp.asarray(-jnp.inf, scores.dtype)

  def _Body(i, carry):
    active, idxs, mask = carry
    s = jnp.where(active, scores, neg_inf)
    best = jnp.argmax(s)
    ok = s[best] > neg_inf
    idxs = idxs.at[i].set(jnp.where(ok, best.astype(jnp.int32), 0))
    mask = mask.at[i].set(ok.astype(jnp.float32))
    suppress = iou[best] > nms_iou_threshold             # includes best
    active = active & ~(suppress & ok)
    return active, idxs, mask

  active0 = scores > score_threshold
  idxs0 = jnp.zeros((max_output_size,), jnp.int32)
  mask0 = jnp.zeros((max_output_size,), jnp.float32)
  _, idxs, mask = jax.lax.fori_loop(
      0, max_output_size, _Body, (active0, idxs0, mask0))
  return idxs, mask


def DecodeWithNMS(predicted_bboxes, classification_scores,
                  nms_iou_threshold=0.3, score_threshold=0.01,
                  max_boxes_per_class=64):
  """Per-class oriented NMS decode (ref detection_decoder.DecodeWithNMS:22,
  `_MultiClassOrientedDecodeWithNMS:73`).

  predicted_bboxes [B, N, 7]; classification_scores [B, N, C] (class 0 =
  background, skipped). Returns NestedMap with per-class padded outputs:
  bboxes [B, C, max, 7], scores [B, C, max], valid_mask [B, C, max].
  """
  b, n, num_classes = classification_scores.shape

  def _OneClass(bboxes, scores):
    idxs, mask = OrientedNMSIndices(
        bboxes, scores, max_boxes_per_class, nms_iou_threshold,
        score_threshold)
    return bboxes[idxs], scores[idxs] * mask, mask

  def _OneExample(bboxes, scores):
    outs = [(jnp.zeros((max_boxes_per_class, 7)),
             jnp.zeros((max_boxes_per_class,)),
             jnp.zeros((max_boxes_per_class,)))]        # class 0: background
    for c in range(1, num_classes):
      outs.append(_OneClass(bboxes, scores[:, c]))
    bb = jnp.stack([o[0] for o in outs])
    ss = jnp.stack([o[1] for o in outs])
    mm = jnp.stack([o[2] for o in outs])
    return bb, ss, mm

  bb, ss, mm = jax.vmap(_OneExample)(predicted_bboxes, classification_scores)
  return NestedMap(bboxes=bb, scores=ss, valid_mask=mm)


def RandomPadOrTrimTo(arrays, num_out, key):
  """Pads (with zeros) or uniformly subsamples rows so dim0 == num_out;
  returns (arrays, padding) (ref RandomPadOrTrimTo:1288). Host-side helper
  for input pipelines; operates on the leading dim of every array."""
  import numpy as np
  n = arrays[0].shape[0]
  rng = np.random.RandomState(int(key) & 0x7FFFFFFF)
  if n == 0:
    idx = np.zeros((0,), np.int64)
  elif n > num_out:
    idx = rng.choice(n, size=num_out, replace=False)
  else:
    idx = np.arange(n)
  out = []
  for a in arrays:
    padded = np.zeros((num_out,) + a.shape[1:], a.dtype)
    padded[:len(idx)] = a[idx]
    out.append(padded)
  padding = np.ones((num_out,), np.float32)
  padding[:len(idx)] = 0.0
  return out, padding


def SceneToDetectionViews(lasers, lpad, boxes, classes, *, grid_size,
                          grid_range_x, grid_range_y, max_pillars,
                          points_per_pillar):
  """Host-side scene -> fixed-shape detector views (shared by the KITTI and
  Waymo file inputs; ref input_preprocessors.py pillar/grid featurization).

  lasers: [N, D] padded points (D >= 3, xyz first); lpad: [N] paddings;
  boxes: iterable of 7-DOF gt; classes: matching class ids. Returns a
  NestedMap with pillar_points [P, Q, D], point_paddings [P, Q],
  pillar_cells [P], cls_targets [g*g], reg_targets [g*g, 7],
  reg_weights [g*g].
  """
  import numpy as np

  g = grid_size
  x_lo, x_hi = grid_range_x
  y_lo, y_hi = grid_range_y
  d = lasers.shape[-1]

  def _CellXY(x, y):
    if not (x_lo <= x < x_hi and y_lo <= y < y_hi):
      return None
    col = int((x - x_lo) / (x_hi - x_lo) * g)
    row = int((y - y_lo) / (y_hi - y_lo) * g)
    return min(col, g - 1), min(row, g - 1)

  pillars = np.zeros((max_pillars, points_per_pillar, d), np.float32)
  ppad = np.ones((max_pillars, points_per_pillar), np.float32)
  cells = np.full((max_pillars,), -1, np.int32)
  cls_t = np.zeros((g * g,), np.int32)
  reg_t = np.zeros((g * g, 7), np.float32)
  reg_w = np.zeros((g * g,), np.float32)
  real = lasers[lpad == 0]
  if len(real):
    cell_of = np.full((len(real),), -1, np.int64)
    for i, pt in enumerate(real):
      cr = _CellXY(float(pt[0]), float(pt[1]))
      if cr is not None:
        cell_of[i] = cr[1] * g + cr[0]
    order = np.argsort(cell_of, kind="stable")
    order = order[cell_of[order] >= 0]
    pi = -1
    last_cell = None
    fill = 0
    for idx in order:
      c = cell_of[idx]
      if c != last_cell:
        pi += 1
        if pi >= max_pillars:
          break
        last_cell = c
        cells[pi] = c
        fill = 0
      if fill < points_per_pillar:
        pillars[pi, fill] = real[idx]
        ppad[pi, fill] = 0.0
        fill += 1
  cell_w = (x_hi - x_lo) / g
  cell_h = (y_hi - y_lo) / g
  for bx, cl in zip(boxes, classes):
    cr = _CellXY(float(bx[0]), float(bx[1]))
    if cr is None:
      continue
    col, row = cr
    cell = row * g + col
    cx_center = x_lo + (col + 0.5) * cell_w
    cy_center = y_lo + (row + 0.5) * cell_h
    cls_t[cell] = cl
    reg_t[cell] = [bx[0] - cx_center, bx[1] - cy_center,
                   bx[2], bx[3], bx[4], bx[5], bx[6]]
    reg_w[cell] = 1.0
  return NestedMap(
      pillar_points=pillars, point_paddings=ppad, pillar_cells=cells,
      cls_targets=cls_t, reg_targets=reg_t, reg_weights=reg_w)
