"""Breakdown AP metrics: AP sliced by ground-truth difficulty dimensions
(ref `lingvo/tasks/car/breakdown_metric.py` ByDistance:252 / ByRotation:371 /
ByNumPoints:471).

Each breakdown partitions boxes into bins (distance from the sensor, box
rotation, points inside the box) and reports a per-bin AP: ground truths
are binned by their own attribute; predictions by theirs when they carry
it (distance, rotation) and by their max-IoU matched gt's attribute when
only gt boxes have it (num points) — so a perfect detector scores 1.0 in
every populated bin. Host-side numpy like ap_metric.
"""

from __future__ import annotations

import math

import numpy as np

from lingvo_tpu.models.car import ap_metric


class BreakdownApMetric:
  """AP per bin of a ground-truth attribute."""

  # matched-gt binning only honors overlaps at least this fraction of the
  # AP match threshold; weaker touches stay pure FPs (KITTI's min-overlap
  # rule for ignored regions)
  _MIN_MATCH_FRACTION = 0.5

  def __init__(self, bin_edges, bin_of_gt, iou_threshold: float = 0.5,
               bin_preds_by_matched_gt: bool = False,
               cumulative: bool = False):
    """bin_edges: labels only (len = num bins); bin_of_gt(gt_box [7]) ->
    bin index in [0, num_bins) or -1 to exclude.

    bin_preds_by_matched_gt: bin each prediction by the attribute of the
    gt box it overlaps most (BEV IoU), not by its own attribute — required
    when the attribute only exists on gt boxes (e.g. point counts, ref
    breakdown_metric.ByNumPoints:471). Unmatched predictions (no
    overlapping gt) are pure false positives and count against every bin,
    matching the KITTI slicing convention.

    cumulative: bin b scores gts with bin <= b (the KITTI easy/moderate/
    hard protocol: moderate includes easy boxes; detections matched to
    HARDER gts are ignored, ref kitti_ap_metric.py gt_ignore semantics).
    """
    self._labels = list(bin_edges)
    self._bin_of_gt = bin_of_gt
    self._bin_preds_by_matched_gt = bin_preds_by_matched_gt
    self._cumulative = cumulative
    self._iou_threshold = iou_threshold
    if cumulative:
      assert bin_preds_by_matched_gt, (
          "cumulative slicing needs matched-gt prediction binning to "
          "implement the ignore-harder-gt rule")
    self._metrics = [ap_metric.ApMetric(iou_threshold)
                     for _ in self._labels]

  _UNMATCHED = -1   # no overlapping same-class gt: pure FP, hits every bin
  _EXCLUDED = -2    # matched a gt that bin_of_gt excluded: not scored

  def _MatchedGtBins(self, pred_boxes, gt_boxes, gt_bins,
                     pred_classes, gt_classes):
    """Bin of each prediction's max-IoU same-class gt (sentinels above).

    Overlaps below _MIN_MATCH_FRACTION of the AP threshold don't count as
    matches: a grazing touch of a harder/excluded gt must stay a pure FP
    rather than vanish from the other slices.
    """
    min_iou = self._MIN_MATCH_FRACTION * self._iou_threshold
    bins = np.full((len(pred_boxes),), self._UNMATCHED, np.int64)
    for i, pb in enumerate(pred_boxes):
      best_iou, best_j = 0.0, -1
      for j, gb in enumerate(gt_boxes):
        if (pred_classes is not None and gt_classes is not None and
            pred_classes[i] != gt_classes[j]):
          continue  # ApMetric matches class-aware; mirror it
        iou = ap_metric.RotatedIou(np.asarray(pb)[:7], np.asarray(gb)[:7])
        if iou > best_iou:
          best_iou, best_j = iou, j
      if best_j >= 0 and best_iou >= min_iou:
        b = gt_bins[best_j]
        bins[i] = b if b >= 0 else self._EXCLUDED
    return bins

  def Update(self, pred_boxes, pred_scores, gt_boxes,
             pred_classes=None, gt_classes=None):
    gt_bins = np.array([self._bin_of_gt(g) for g in gt_boxes], np.int64) \
        if len(gt_boxes) else np.zeros((0,), np.int64)
    if not len(pred_boxes):
      pred_bins = np.zeros((0,), np.int64)
    elif self._bin_preds_by_matched_gt:
      pred_bins = self._MatchedGtBins(pred_boxes, gt_boxes, gt_bins,
                                      pred_classes, gt_classes)
    else:
      pred_bins = np.array([self._bin_of_gt(g) for g in pred_boxes],
                           np.int64)
    for b, metric in enumerate(self._metrics):
      if self._cumulative:
        sel = (gt_bins >= 0) & (gt_bins <= b)
        psel = (pred_bins >= 0) & (pred_bins <= b)
      else:
        sel = gt_bins == b
        psel = pred_bins == b
      if self._bin_preds_by_matched_gt:
        # pure FPs penalize every bin; matched-to-excluded preds score
        # nowhere (their gt was deliberately out of protocol)
        psel = psel | (pred_bins == self._UNMATCHED)
      metric.Update(
          pred_boxes[psel], pred_scores[psel], gt_boxes[sel],
          pred_classes=(pred_classes[psel] if pred_classes is not None
                        else None),
          gt_classes=(gt_classes[sel] if gt_classes is not None else None))

  @property
  def value(self) -> dict:
    return {label: m.value for label, m in zip(self._labels, self._metrics)}


def ByDistance(max_distance: float = 80.0, num_bins: int = 4,
               iou_threshold: float = 0.5) -> BreakdownApMetric:
  """AP binned by BEV distance of the gt box center from the origin
  (ref breakdown_metric.ByDistance:252)."""
  edges = np.linspace(0.0, max_distance, num_bins + 1)
  labels = [f"dist_{edges[i]:.0f}_{edges[i + 1]:.0f}"
            for i in range(num_bins)]

  def _Bin(gt):
    d = math.hypot(float(gt[0]), float(gt[1]))
    if d >= max_distance:
      return num_bins - 1
    return int(d / max_distance * num_bins)

  return BreakdownApMetric(labels, _Bin, iou_threshold)


def ByRotation(num_bins: int = 4,
               iou_threshold: float = 0.5) -> BreakdownApMetric:
  """AP binned by gt heading folded into [0, pi) (ref ByRotation:371)."""
  labels = [f"rot_{i}_of_{num_bins}" for i in range(num_bins)]

  def _Bin(gt):
    phi = float(gt[6]) % math.pi
    return min(int(phi / math.pi * num_bins), num_bins - 1)

  return BreakdownApMetric(labels, _Bin, iou_threshold)


def ByNumPoints(edges=(1, 50, 200, 100000),
                iou_threshold: float = 0.5):
  """AP binned by the number of laser points inside the gt box
  (ref ByNumPoints:471). The caller must annotate gt boxes with a point
  count in column 7 (i.e. pass [..., 8] boxes: 7-DOF + count); predictions
  are 7-DOF and are binned by their max-IoU matched gt's count."""
  labels = [f"pts_lt_{e}" for e in edges]

  def _Bin(gt):
    n = float(gt[7]) if len(gt) > 7 else 0.0
    for i, e in enumerate(edges):
      if n < e:
        return i
    return len(edges) - 1

  return BreakdownApMetric(labels, _Bin, iou_threshold,
                           bin_preds_by_matched_gt=True)


def ByKittiDifficulty(iou_threshold: float = 0.5) -> BreakdownApMetric:
  """Cumulative easy/moderate/hard AP per the KITTI protocol (ref
  `kitti_ap_metric.py`: moderate includes easy gts; matches to harder gts
  are ignored). Annotate gt boxes with the difficulty code in column 7
  (0 easy / 1 moderate / 2 hard, -1 to exclude; see
  kitti_input.KittiDifficulty)."""
  labels = ["easy", "moderate", "hard"]

  def _Bin(gt):
    return int(gt[7]) if len(gt) > 7 else 2

  return BreakdownApMetric(labels, _Bin, iou_threshold,
                           bin_preds_by_matched_gt=True, cumulative=True)


def ByDifficulty(iou_threshold: float = 0.5) -> BreakdownApMetric:
  """AP per Waymo difficulty level (LEVEL_1 / LEVEL_2, ref waymo metrics
  config + `breakdown_metric.py` difficulty slicing). Annotate gt boxes
  with the difficulty in column 7 ([..., 8] boxes); predictions are 7-DOF
  and bin by their matched gt."""
  labels = ["level_1", "level_2"]

  def _Bin(gt):
    d = int(gt[7]) if len(gt) > 7 else 1
    return min(max(d, 1), 2) - 1

  return BreakdownApMetric(labels, _Bin, iou_threshold,
                           bin_preds_by_matched_gt=True)


def CountPointsInBoxes(points: np.ndarray, boxes: np.ndarray) -> np.ndarray:
  """points [N, >=3], boxes [G, 7] -> [G] count of points inside each
  (rotated BEV footprint x z-extent)."""
  if len(points) == 0 or len(boxes) == 0:
    return np.zeros((len(boxes),), np.int64)
  counts = np.zeros((len(boxes),), np.int64)
  for g, b in enumerate(boxes):
    dx, dy = points[:, 0] - b[0], points[:, 1] - b[1]
    c, s = math.cos(-b[6]), math.sin(-b[6])
    lx = dx * c - dy * s
    ly = dx * s + dy * c
    inside = ((np.abs(lx) <= b[3] / 2) & (np.abs(ly) <= b[4] / 2) &
              (np.abs(points[:, 2] - b[2]) <= b[5] / 2))
    counts[g] = int(inside.sum())
  return counts
