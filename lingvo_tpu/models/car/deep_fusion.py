"""DeepFusion: camera+lidar fusion detection (ref
`lingvo/tasks/car/deep_fusion.py` MultiModalFeaturizer / LearnableAlign,
arXiv:2203.08195).

TPU-first re-design: the camera tower is a strided conv stack producing
patch tokens, and LearnableAlign is one batched cross-attention einsum —
pillar features query the image tokens (paper §3.3: lidar features as
queries, camera features as keys/values), followed by the concat+FC fusion
block. Everything is static-shape dense math on the MXU; no per-point
image projection gathers (the reference's projection-based alignment
becomes a learned attention over all patches, which subsumes it for the
fused-feature contract).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.core.py_utils import WeightParams
from lingvo_tpu.models.car import pillars


class CameraFeaturizer(base_layer.BaseLayer):
  """[b, H, W, 3] camera image -> [b, T, C] patch tokens (ref
  ImageFeatureExtractorBuilder conv tower)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("input_channels", 3, "Image channels.")
    p.Define("filter_counts", [32, 64], "Channels per stride-2 block.")
    p.Define("image_channels", 64, "Output token dim.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    cin = p.input_channels
    convs = []
    for cout in p.filter_counts:
      convs.append(layers_lib.Conv2DLayer.Params().Set(
          filter_shape=(3, 3, cin, cout), filter_stride=(2, 2),
          activation="RELU", batch_norm=False, has_bias=True))
      cin = cout
    self.CreateChildren("convs", convs)
    self.CreateChild(
        "proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=cin, output_dim=p.image_channels, activation="NONE"))

  def FProp(self, theta, images):
    x = self.ToFPropDtype(images)
    for i, conv in enumerate(self.convs):
      x = conv.FProp(theta.convs[i], x)
    b, h, w, c = x.shape
    return self.proj.FProp(theta.proj, x.reshape(b, h * w, c))


class LearnableAlign(base_layer.BaseLayer):
  """Cross-attention fusion: lidar queries, camera keys/values (ref
  LearnableAlignBuilder: LidarEmbedding/ImageEmbedding/FC/Fusion)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("lidar_channels", 64, "Pillar feature dim.")
    p.Define("image_channels", 64, "Camera token dim.")
    p.Define("qkv_channels", 64, "Attention projection dim.")
    p.Define("atten_dropout_prob", 0.0, "Attention dropout (ref 0.3).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "q_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.lidar_channels, output_dim=p.qkv_channels,
            activation="NONE", has_bias=False))
    self.CreateChild(
        "k_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.image_channels, output_dim=p.qkv_channels,
            activation="NONE", has_bias=False))
    self.CreateChild(
        "v_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.image_channels, output_dim=p.qkv_channels,
            activation="NONE", has_bias=False))
    self.CreateChild(
        "out_proj",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.qkv_channels, output_dim=p.image_channels,
            activation="NONE"))
    self.CreateChild(
        "fusion",
        layers_lib.ProjectionLayer.Params().Set(
            input_dim=p.image_channels + p.lidar_channels,
            output_dim=p.lidar_channels, activation="RELU"))
    self.CreateChild("dropout",
                     layers_lib.DeterministicDropoutLayer.Params())

  def FProp(self, theta, pillar_feats, camera_tokens, pillar_cells=None):
    """[b, P, C_l] pillars x [b, T, C_i] camera -> fused [b, P, C_l].

    Empty pillars (cell -1) pass through unfused so padding never reads
    camera context.
    """
    p = self.p
    q = self.q_proj.FProp(theta.q_proj, pillar_feats)     # [b,P,qk]
    k = self.k_proj.FProp(theta.k_proj, camera_tokens)    # [b,T,qk]
    v = self.v_proj.FProp(theta.v_proj, camera_tokens)
    logits = jnp.einsum("bpd,btd->bpt", q, k) / math.sqrt(p.qkv_channels)
    probs = jax.nn.softmax(logits.astype(jnp.float32), -1).astype(q.dtype)
    if p.atten_dropout_prob > 0:
      probs = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), probs,
          keep_prob=1.0 - p.atten_dropout_prob)
    ctx = self.out_proj.FProp(theta.out_proj,
                              jnp.einsum("bpt,btd->bpd", probs, v))
    fused = self.fusion.FProp(
        theta.fusion, jnp.concatenate([ctx, pillar_feats], axis=-1))
    if pillar_cells is not None:
      valid = (pillar_cells >= 0)[..., None]
      fused = jnp.where(valid, fused, pillar_feats)
    return fused


class DeepFusionModel(pillars.PointPillarsModel):
  """PointPillars with LearnableAlign camera fusion before the BEV
  backbone (ref MultiModalFeaturizer wiring). Batch adds `camera`
  [b, H, W, 3]."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("camera_featurizer", CameraFeaturizer.Params(),
             "Camera tower.")
    p.Define("aligner", LearnableAlign.Params(), "Fusion cross-attention.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("camera_featurizer", self.p.camera_featurizer)
    self.CreateChild("aligner", self.p.aligner)

  def ComputePredictions(self, theta, input_batch):
    feats = self.featurizer.FProp(
        self.ChildTheta(theta, "featurizer"),
        input_batch.pillar_points, input_batch.point_paddings)
    tokens = self.camera_featurizer.FProp(
        self.ChildTheta(theta, "camera_featurizer"), input_batch.camera)
    fused = self.aligner.FProp(
        self.ChildTheta(theta, "aligner"), feats, tokens,
        pillar_cells=input_batch.pillar_cells)
    cls_logits, box_residuals = self.backbone.FProp(
        self.ChildTheta(theta, "backbone"), fused,
        input_batch.pillar_cells)
    return NestedMap(cls_logits=cls_logits, box_residuals=box_residuals)
