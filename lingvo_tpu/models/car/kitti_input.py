"""KITTI-format input: label/calibration parsing + a file-based scene input
generator over the native record yielder.

Re-designs `lingvo/tasks/car/kitti_input_generator.py` +
`tools/kitti_data.py`: the same label-line grammar (15/16 tokens,
camera-frame h/w/l + x/y/z + rotation_y), the same camera->velodyne box
conversion (z recentred to the box middle, phi = -rotation_y - pi/2), and
the same canonical 7-DOF (x, y, z, dx, dy, dz, phi) output — but records
flow through the C++ shuffle-ring yielder as JSON-line scenes instead of
TFRecords of TF Examples, and target assignment happens on device
(`detection_3d.AssignAnchors`), not in the input graph.

Record format (one JSON object per line):
  {"points": [[x, y, z, reflectance], ...],     # velodyne frame
   "labels": ["Car 0.00 0 ...", ...],           # raw KITTI label lines
   "calib": {"R0_rect": [9 floats], "Tr_velo_to_cam": [12 floats]}}
`calib` may be omitted: boxes are then taken to already be in the velodyne
frame with the nominal axis swap (the camera at the velodyne origin).
"""

from __future__ import annotations

import json

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap

KITTI_TYPES = ("Car", "Van", "Truck", "Pedestrian", "Person_sitting",
               "Cyclist", "Tram", "Misc", "DontCare")
# the reference's standard class splits (kitti train uses these three)
CLASS_IDS = {"Car": 1, "Pedestrian": 2, "Cyclist": 3}


def ParseKittiLabelLine(line: str) -> dict:
  """One label line -> dict (ref kitti_data.LoadLabelFile:89 grammar)."""
  parts = line.strip().split(" ")
  if len(parts) not in (15, 16):
    raise ValueError(f"expected 15/16 tokens, got {len(parts)}: {line!r}")
  if len(parts) == 15:
    parts.append("-1")
  (obj_type, truncated, occluded, alpha, bl, bt, br, bb, h, w, l,
   x, y, z, rot_y, score) = parts
  if obj_type not in KITTI_TYPES:
    raise ValueError(f"invalid type {obj_type!r}")
  return {
      "type": obj_type,
      "truncated": float(truncated),
      "occluded": int(occluded),
      "alpha": float(alpha),
      "bbox": [float(v) for v in (bl, bt, br, bb)],
      "dimensions": [float(v) for v in (h, w, l)],   # height, width, length
      "location": [float(v) for v in (x, y, z)],     # camera frame
      "rotation_y": float(rot_y),
      "score": float(score),
  }


def KittiDifficulty(obj: dict) -> int:
  """KITTI protocol difficulty from 2D bbox height / occlusion /
  truncation (ref kitti eval protocol thresholds used by
  `kitti_ap_metric.py` MinHeight2D/DifficultyLevels):
  0 easy (h>=40px, occ 0, trunc<=0.15), 1 moderate (h>=25, occ<=1,
  trunc<=0.3), 2 hard (h>=25, occ<=2, trunc<=0.5), -1 excluded."""
  bl, bt, br, bb = obj["bbox"]
  height = abs(bb - bt)
  occ = obj["occluded"]
  trunc = obj["truncated"]
  if height >= 40.0 and occ <= 0 and trunc <= 0.15:
    return 0
  if height >= 25.0 and occ <= 1 and trunc <= 0.30:
    return 1
  if height >= 25.0 and occ <= 2 and trunc <= 0.50:
    return 2
  return -1


def VeloToCameraTransformation(calib: dict) -> np.ndarray:
  """4x4 velodyne->camera matrix from R0_rect (3x3) + Tr_velo_to_cam (3x4)
  (ref kitti_data.VeloToCameraTransformation:250)."""
  r0 = np.eye(4)
  r0[:3, :3] = np.asarray(calib["R0_rect"], np.float64).reshape(3, 3)
  tr = np.eye(4)
  tr[:3, :4] = np.asarray(calib["Tr_velo_to_cam"], np.float64).reshape(3, 4)
  return r0 @ tr


def CameraToVeloTransformation(calib: dict) -> np.ndarray:
  return np.linalg.pinv(VeloToCameraTransformation(calib))


_NOMINAL_CAM_TO_VELO = np.array(
    # velo_x = cam_z (forward), velo_y = -cam_x (left), velo_z = -cam_y (up)
    [[0.0, 0, 1, 0], [-1, 0, 0, 0], [0, -1, 0, 0], [0, 0, 0, 1]])


def KittiObjectToBBox3D(obj: dict, cam_to_velo: np.ndarray | None = None):
  """KITTI object -> canonical (x, y, z, dx, dy, dz, phi) in the velodyne
  frame, or None when the object has no 3D info (ref
  kitti_data._KITTIObjectToBBox3D:316)."""
  height, width, length = obj["dimensions"]
  if height == -1 or width == -1 or length == -1:
    return None
  if cam_to_velo is None:
    cam_to_velo = _NOMINAL_CAM_TO_VELO
  xyz1 = np.asarray(list(obj["location"]) + [1.0])
  x, y, z = (cam_to_velo @ xyz1)[:3]
  z += height / 2.0  # KITTI anchors z at the box bottom
  phi = -obj["rotation_y"] - np.pi / 2.0
  return np.array([x, y, z, length, width, height, phi], np.float32)


class KittiSceneInputGenerator(
    base_input_generator.FileBasedSequenceInputGenerator):
  """JSON-line KITTI scenes -> fixed-shape detection batches.

  Emits the same fields as SyntheticCarInput (lasers/gt boxes + pillar and
  grid-target views), so StarNet and PointPillars train from real files
  unchanged."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("max_points", 512, "Lasers padded/subsampled to this count.")
    p.Define("max_objects", 8, "GT boxes padded to this count.")
    p.Define("grid_size", 16, "BEV grid cells per axis for the pillars view.")
    p.Define("grid_range_x", (0.0, 16.0),
             "(min, max) world x covered by the grid; real KITTI scenes "
             "want e.g. (0, 70.4).")
    p.Define("grid_range_y", (0.0, 16.0),
             "(min, max) world y; real KITTI wants e.g. (-40, 40).")
    p.Define("max_pillars", 64, "P.")
    p.Define("points_per_pillar", 8, "N.")
    p.Define("num_classes", 3,
             "Foreground classes kept, in CLASS_IDS order (2 drops "
             "Cyclist, 1 keeps only Car).")
    p.Define("augmentors", [],
             "List of augmentation.Augmentor Params applied per scene "
             "(points + gt boxes) before view assembly. Configure on the "
             "Train() dataset only (ref input_preprocessors.py train-time "
             "preprocessor lists).")
    p.bucket_upper_bound = [1]
    return p

  def __init__(self, params):
    # scenes are fixed-shape: always one bucket of exactly batch_size
    # (set here, not in Params() — batch_size is configured after Params())
    params = params.Copy()
    params.bucket_upper_bound = [1]
    params.bucket_batch_limit = [params.batch_size or 2]
    super().__init__(params)
    self._record_counter = 0
    from lingvo_tpu.models.car import augmentation
    self._augmentors = augmentation.BuildPipeline(self.p.augmentors)

  def ProcessRecord(self, record: bytes):
    p = self.p
    self._record_counter += 1
    try:
      scene = json.loads(record.decode("utf-8"))
      if not isinstance(scene, dict):
        return None
      labels = [ParseKittiLabelLine(line)
                for line in scene.get("labels", [])]
      pts = np.asarray(scene.get("points", []), np.float32).reshape(-1, 4)
      cam_to_velo = None
      if scene.get("calib"):
        cam_to_velo = CameraToVeloTransformation(scene["calib"])
    except (UnicodeDecodeError, json.JSONDecodeError, ValueError, TypeError,
            KeyError):
      return None  # malformed record/geometry: drop, never kill the pipeline
    boxes, classes, difficulties = [], [], []
    for obj in labels:
      cls_id = CLASS_IDS.get(obj["type"], 0)
      if not 0 < cls_id <= p.num_classes:
        continue  # DontCare / out-of-split types are dropped, ref behavior
      bbox = KittiObjectToBBox3D(obj, cam_to_velo)
      if bbox is None:
        continue
      boxes.append(bbox)
      classes.append(cls_id)
      difficulties.append(KittiDifficulty(obj))

    if self._augmentors:
      from lingvo_tpu.models.car import augmentation
      scene_nm = augmentation.MakeScene(pts, np.asarray(
          boxes, np.float32).reshape(-1, 7), classes)
      scene_nm.difficulty = np.asarray(difficulties, np.int32)
      scene_nm = augmentation.ApplyPipeline(
          self._augmentors, scene_nm, seed=self._record_counter)
      pts = scene_nm.points
      boxes = list(scene_nm.boxes)
      classes = list(scene_nm.classes)
      difficulties = list(scene_nm.difficulty)

    # lasers: subsample-or-pad to max_points, varying the subsample per
    # record read so repeated epochs see different points
    from lingvo_tpu.models.car import detection_3d
    (lasers,), lpad = detection_3d.RandomPadOrTrimTo(
        [pts], p.max_points, key=self._record_counter * 2654435761 + len(pts))

    gt_boxes = np.zeros((p.max_objects, 7), np.float32)
    gt_classes = np.zeros((p.max_objects,), np.int32)
    gt_difficulty = np.full((p.max_objects,), -1, np.int32)
    for i, (bx, cl, df) in enumerate(zip(boxes, classes, difficulties)):
      if i >= p.max_objects:
        break
      gt_boxes[i] = bx
      gt_classes[i] = cl
      gt_difficulty[i] = df

    # pillar + grid-target views (shared assembly), with world->grid
    # scaling so real KITTI ranges (x in [0, 70.4), y in [-40, 40)) map
    # onto the g x g BEV grid
    views = detection_3d.SceneToDetectionViews(
        lasers, lpad, boxes, classes,
        grid_size=p.grid_size, grid_range_x=p.grid_range_x,
        grid_range_y=p.grid_range_y, max_pillars=p.max_pillars,
        points_per_pillar=p.points_per_pillar)
    views.update(
        bucket_key=1,
        lasers=lasers, laser_paddings=lpad,
        gt_boxes=gt_boxes, gt_classes=gt_classes,
        gt_difficulty=gt_difficulty)
    return views
