"""Imports every params module so the registry is fully populated.

Ref: `lingvo/model_imports.py` — here a static import list (cheap; the
dynamic per-prefix import in model_registry handles the common CLI path).
"""

from lingvo_tpu.models.image.params import mnist  # noqa: F401

try:
  from lingvo_tpu.models.lm.params import synthetic_packed_input  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.lm.params import one_billion_wds  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.lm.params import wiki_bert  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.mt.params import wmt14_en_de  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.asr.params import librispeech  # noqa: F401
except ImportError:
  pass

try:
  from lingvo_tpu.models.punctuator.params import codelab  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.milan.params import dual_encoder  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.car.params import kitti  # noqa: F401
except ImportError:
  pass
try:
  from lingvo_tpu.models.car.params import waymo  # noqa: F401
except ImportError:
  pass
