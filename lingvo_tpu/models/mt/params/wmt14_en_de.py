"""WMT'14 en-de transformer configs (ref:
`lingvo/tasks/mt/params/wmt14_en_de.py:27` WmtEnDeTransformerBase).

Same model shapes as the reference's base transformer (model_dim 512, 6+6
layers, 8 heads, ffn 2048, label smoothing 0.1, transformer LR schedule);
input here is the synthetic MT generator (real WMT data needs the C++ record
pipeline + BPE tokenizer — see ops/).
"""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.mt import input_generator
from lingvo_tpu.models.mt import model as mt_model


@model_registry.RegisterSingleTaskModel
class WmtEnDeTransformerBase(base_model_params.SingleTaskModelParams):
  """Base transformer (ref wmt14_en_de.py:27)."""

  BATCH_SIZE = 64
  VOCAB = 32000
  MODEL_DIM = 512
  NUM_LAYERS = 6
  NUM_HEADS = 8
  HIDDEN_DIM = 2048
  SRC_LEN = 96
  TGT_LEN = 96

  def Train(self):
    return input_generator.SyntheticMtInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        src_seq_len=self.SRC_LEN, tgt_seq_len=self.TGT_LEN)

  def Test(self):
    return input_generator.SyntheticMtInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        src_seq_len=self.SRC_LEN, tgt_seq_len=self.TGT_LEN, seed=123)

  def Task(self):
    p = mt_model.TransformerModel.Params()
    p.name = "wmt14_en_de"
    for enc_dec in (p.encoder, p.decoder):
      enc_dec.vocab_size = self.VOCAB
      enc_dec.model_dim = self.MODEL_DIM
      enc_dec.num_layers = self.NUM_LAYERS
      enc_dec.num_heads = self.NUM_HEADS
      enc_dec.hidden_dim = self.HIDDEN_DIM
      enc_dec.residual_dropout_prob = 0.1
      enc_dec.input_dropout_prob = 0.1
    p.decoder.label_smoothing = 0.1
    p.decoder.beam_search.num_hyps_per_beam = 4
    p.decoder.beam_search.target_seq_len = self.TGT_LEN
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1.0,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=4000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=0.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeTransformerTiny(WmtEnDeTransformerBase):
  """Smoke-test scale."""

  BATCH_SIZE = 8
  VOCAB = 64
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  HIDDEN_DIM = 64
  SRC_LEN = 10
  TGT_LEN = 12

  def Task(self):
    p = super().Task()
    for enc_dec in (p.encoder, p.decoder):
      enc_dec.residual_dropout_prob = 0.0
      enc_dec.input_dropout_prob = 0.0
    # At this scale a flat LR converges far faster than the rsqrt schedule
    # (verified: acc 0.96 / test BLEU 1.0 at 1500 steps).
    p.train.learner.learning_rate = 1e-3
    p.train.learner.lr_schedule = sched_lib.Constant.Params()
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeTransformerBpe(WmtEnDeTransformerBase):
  """Real-data WMT'14 through the native pipeline: tab-separated
  "en<TAB>de" text shards -> shared BPE (ref `wmt14_en_de.py` wordpiece
  datasets + `BpeWordsToIds` kernels; set LINGVO_TPU_DATA_DIR to a root
  with `wmt14/train.en-de.tsv*`, `wmt14/bpe.codes` and `wmt14/bpe.vocab`)."""

  def _Input(self, pattern: str, seed: int):
    import os
    from lingvo_tpu.core import tokenizers
    data_dir = os.environ.get("LINGVO_TPU_DATA_DIR", "/tmp/lingvo_tpu_data")
    return input_generator.TextMtInput.Params().Set(
        file_pattern=f"text:{data_dir}/wmt14/{pattern}",
        tokenizer=tokenizers.BpeTokenizer.Params().Set(
            codes_filepath=f"{data_dir}/wmt14/bpe.codes",
            vocab_filepath=f"{data_dir}/wmt14/bpe.vocab",
            vocab_size=self.VOCAB),
        source_max_length=self.SRC_LEN,
        target_max_length=self.TGT_LEN,
        bucket_upper_bound=[24, 48, 96],
        bucket_batch_limit=[4 * self.BATCH_SIZE, 2 * self.BATCH_SIZE,
                            self.BATCH_SIZE],
        seed=seed)

  def Train(self):
    return self._Input("train.en-de.tsv*", seed=301)

  def Test(self):
    p = self._Input("newstest2014.en-de.tsv", seed=7)
    return p.Set(shuffle=False, max_epochs=1, require_sequential_order=True)


@model_registry.RegisterSingleTaskModel
class WmtEnDeRNMTPlus(base_model_params.SingleTaskModelParams):
  """RNMT+ recurrent encoder-decoder (ref the reference's RNMT MT family;
  arXiv:1804.09849 recipe)."""

  BATCH_SIZE = 64
  VOCAB = 32000
  MODEL_DIM = 512
  NUM_LAYERS = 4
  SRC_LEN = 96
  TGT_LEN = 96

  def Train(self):
    return input_generator.SyntheticMtInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        src_seq_len=self.SRC_LEN, tgt_seq_len=self.TGT_LEN)

  def Test(self):
    return self.Train().Set(seed=123)

  def Task(self):
    from lingvo_tpu.models.mt import rnmt
    p = rnmt.RNMTModel.Params()
    p.name = "wmt14_en_de_rnmt"
    p.encoder.vocab_size = self.VOCAB
    p.encoder.model_dim = self.MODEL_DIM
    p.encoder.num_layers = self.NUM_LAYERS
    p.decoder.vocab_size = self.VOCAB
    p.decoder.model_dim = self.MODEL_DIM
    p.decoder.num_layers = self.NUM_LAYERS
    p.decoder.max_decode_len = self.TGT_LEN
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=4000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=5.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeRNMTPlusTiny(WmtEnDeRNMTPlus):
  """CPU-smoke scale."""

  BATCH_SIZE = 4
  VOCAB = 64
  MODEL_DIM = 16
  NUM_LAYERS = 2
  SRC_LEN = 10
  TGT_LEN = 10

  def Task(self):
    p = super().Task()
    p.decoder.atten_hidden_dim = 16
    p.train.max_steps = 60
    p.train.tpu_steps_per_loop = 20
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeMassPretrain(WmtEnDeTransformerBase):
  """MASS masked-seq2seq pretraining over monolingual data (ref
  `core/ops/mass_op.cc` + the MASS recipes under `tasks/mt/params/`):
  same transformer as WmtEnDeTransformerBase, trained to reconstruct
  masked spans; fine-tune by warm-starting the MT config from its
  checkpoint (core/checkpointer.py init_from_checkpoint_rules)."""

  def Train(self):
    return input_generator.SyntheticMassInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        seq_len=self.SRC_LEN)

  def Test(self):
    return self.Train().Set(seed=123)

  def Task(self):
    p = super().Task()
    p.name = "wmt14_en_de_mass"
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeMassPretrainTiny(WmtEnDeTransformerTiny):
  """Smoke-scale MASS pretraining (pairs with WmtEnDeTransformerTiny for
  the pretrain -> fine-tune path)."""

  def Train(self):
    return input_generator.SyntheticMassInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        seq_len=self.SRC_LEN)

  def Test(self):
    return self.Train().Set(seed=123)

  def Task(self):
    p = super().Task()
    p.name = "wmt14_en_de_mass_tiny"
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeMassFinetuneTiny(WmtEnDeTransformerTiny):
  """Fine-tune fixture whose source sentences share the MASS pretraining
  distribution (strided sequences) — the pretrain -> fine-tune pair models
  monolingual pretraining + same-domain translation."""

  def Train(self):
    return super().Train().Set(strided=True)

  def Test(self):
    return super().Test().Set(strided=True)

  def Task(self):
    p = super().Task()
    p.name = "wmt14_en_de_mass_ft"
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeXEnDec(WmtEnDeTransformerBase):
  """XEnDec crossover joint training (ref
  `tasks/mt/params/xendec/wmt14_en_de.py` WmtEnDeXEnDec, arXiv:2106.04060)."""

  def Task(self):
    from lingvo_tpu.models.mt import xendec
    base = super().Task()
    p = xendec.TransformerXEnDecModel.Params()
    # adopt the base transformer geometry + training recipe
    for name in ("encoder", "decoder", "train", "name"):
      p.Set(**{name: base.Get(name)})
    p.name = "wmt14_en_de_xendec"
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeXEnDecTiny(WmtEnDeTransformerTiny):
  """Smoke-scale XEnDec."""

  def Task(self):
    from lingvo_tpu.models.mt import xendec
    base = super().Task()
    p = xendec.TransformerXEnDecModel.Params()
    for name in ("encoder", "decoder", "train", "name"):
      p.Set(**{name: base.Get(name)})
    p.name = "wmt14_en_de_xendec_tiny"
    # at smoke scale the full-weight crossover loss drowns the supervised
    # gradient; the paper's 1.0 default stays on the full-size config
    p.loss_mix_weight = 0.5
    return p


@model_registry.RegisterSingleTaskModel
class WmtEnDeRealShardSmall(base_model_params.SingleTaskModelParams):
  """REAL-corpus WMT'14 en-de convergence config (CPU-feasible).

  Trains on the 8,941 professionally-translated sentence pairs in the
  reference's shipped t2t wordpiece shard
  (`lingvo/tasks/mt/testdata/translate_ende_wmt32k-train-00511-of-00512`),
  converted once with `tools/t2t_to_jsonl.py` into
  `$LINGVO_TPU_DATA_DIR/wmt14_real/{train,dev}.jsonl` (dev = held-out tail;
  `tools/wmt_convergence.py` does the prep + split + measured run). This is
  the framework's non-synthetic MT quality trajectory: real text, real
  wordpiece distribution, token-level corpus BLEU on held-out data.

  Downsized transformer (d=256, 2+2 layers) so the trajectory is measurable
  on CPU; the full-size recipe is WmtEnDeTransformerBase.
  """

  VOCAB = 33792  # t2t wmt32k vocab (max observed id 33701), padded to 8x
  MODEL_DIM = 256
  NUM_LAYERS = 2
  NUM_HEADS = 4
  HIDDEN_DIM = 1024
  MAX_LEN = 56   # covers p90 of the shard; overlong pairs are dropped
  BATCH_SIZE = 32

  def _Input(self, name: str, seed: int):
    import os
    data_dir = os.environ.get("LINGVO_TPU_DATA_DIR", "/tmp/lingvo_tpu_data")
    return input_generator.IdsMtInput.Params().Set(
        file_pattern=f"text:{data_dir}/wmt14_real/{name}",
        source_max_length=self.MAX_LEN,
        target_max_length=self.MAX_LEN,
        bucket_upper_bound=[16, 24, 32, 56],
        bucket_batch_limit=[3 * self.BATCH_SIZE, 2 * self.BATCH_SIZE,
                            3 * self.BATCH_SIZE // 2, self.BATCH_SIZE],
        seed=seed)

  def Train(self):
    return self._Input("train.jsonl", seed=301)

  def Dev(self):
    return self._Input("dev.jsonl", seed=7).Set(
        shuffle=False, max_epochs=1, require_sequential_order=True)

  def Test(self):
    return self.Dev()

  def Task(self):
    p = mt_model.TransformerModel.Params()
    p.name = "wmt14_en_de_real_small"
    for enc_dec in (p.encoder, p.decoder):
      enc_dec.vocab_size = self.VOCAB
      enc_dec.model_dim = self.MODEL_DIM
      enc_dec.num_layers = self.NUM_LAYERS
      enc_dec.num_heads = self.NUM_HEADS
      enc_dec.hidden_dim = self.HIDDEN_DIM
      enc_dec.residual_dropout_prob = 0.1
      enc_dec.input_dropout_prob = 0.1
    p.decoder.label_smoothing = 0.1
    # t2t convention: no reserved SOS (pad=0 starts decode), eos=1
    p.decoder.beam_search.target_sos_id = 0
    p.decoder.beam_search.target_eos_id = 1
    p.decoder.beam_search.num_hyps_per_beam = 4
    p.decoder.beam_search.target_seq_len = self.MAX_LEN
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1.0,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=500, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=0.0)
    p.train.max_steps = 4000
    p.train.tpu_steps_per_loop = 50
    return p
