"""WMT'16 Multi30k En->De caption translation configs (ref
`lingvo/tasks/mt/params/wmtm16_en_de.py:26` WmtCaptionEnDeTransformer — the
reference's only published end-task quality baseline: ">30 BLEU in <10k
steps on a single GPU", `tasks/mt/README.md:84-86`).

Same model recipe as the reference: 2k wordpiece vocab, model_dim 256,
2+2 layers, 2 heads, ffn 512, dropout 0.2, transformer LR schedule with
warmup 1000, 12k max steps, 29k-sample training set. Data layout: set
LINGVO_TPU_DATA_DIR to a root containing `wmtm16/train.en-de.tsv*` (+ BPE
`wmtm16/bpe.codes`/`bpe.vocab`) prepared from the Multi30k corpus; the
dataset itself is not redistributable here, so the registered config is the
measuring instrument for the reference's BLEU bar once the corpus is
mounted.

`WmtEnDeRealShardSmall` (wmt14_en_de.py) is the companion config that IS
runnable in this sandbox on real data — see its docstring.
"""

from __future__ import annotations

import os

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.mt import input_generator
from lingvo_tpu.models.mt import model as mt_model


@model_registry.RegisterSingleTaskModel
class WmtCaptionEnDeTransformer(base_model_params.SingleTaskModelParams):
  """Multi30k caption transformer, reference shapes (wmtm16_en_de.py:26)."""

  VOCAB = 2000
  MODEL_DIM = 256
  HIDDEN_DIM = 512
  NUM_HEADS = 2
  NUM_LAYERS = 2
  SRC_LEN = 70   # ref train bucket_upper_bound[-1]=75; eval 98
  TGT_LEN = 70
  NUM_SAMPLES = 29000

  def _Input(self, pattern: str, seed: int):
    from lingvo_tpu.core import tokenizers
    data_dir = os.environ.get("LINGVO_TPU_DATA_DIR", "/tmp/lingvo_tpu_data")
    return input_generator.TextMtInput.Params().Set(
        file_pattern=f"text:{data_dir}/wmtm16/{pattern}",
        tokenizer=tokenizers.BpeTokenizer.Params().Set(
            codes_filepath=f"{data_dir}/wmtm16/bpe.codes",
            vocab_filepath=f"{data_dir}/wmtm16/bpe.vocab",
            vocab_size=self.VOCAB),
        source_max_length=self.SRC_LEN,
        target_max_length=self.TGT_LEN,
        # ref train buckets [14,17,20,24,29,35,45,75] — captions are short
        bucket_upper_bound=[14, 20, 29, 45, 70],
        bucket_batch_limit=[128, 96, 64, 48, 32],
        seed=seed)

  def Train(self):
    return self._Input("train.en-de.tsv*", seed=0)

  def Dev(self):
    p = self._Input("val.en-de.tsv", seed=27182818)
    return p.Set(shuffle=False, max_epochs=1, require_sequential_order=True)

  def Test(self):
    p = self._Input("test.en-de.tsv", seed=7)
    return p.Set(shuffle=False, max_epochs=1, require_sequential_order=True)

  def Task(self):
    p = mt_model.TransformerModel.Params()
    p.name = "wmtm16_en_de_caption"
    for enc_dec in (p.encoder, p.decoder):
      enc_dec.vocab_size = self.VOCAB
      enc_dec.model_dim = self.MODEL_DIM
      enc_dec.num_layers = self.NUM_LAYERS
      enc_dec.num_heads = self.NUM_HEADS
      enc_dec.hidden_dim = self.HIDDEN_DIM
      enc_dec.residual_dropout_prob = 0.2
      enc_dec.input_dropout_prob = 0.2
    p.decoder.label_smoothing = 0.1
    p.decoder.beam_search.num_hyps_per_beam = 4
    p.decoder.beam_search.target_seq_len = self.TGT_LEN
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1.0,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.TransformerSchedule.Params().Set(
            warmup_steps=1000, model_dim=self.MODEL_DIM),
        clip_gradient_norm_to_value=0.0)
    p.train.max_steps = 12000
    p.train.tpu_steps_per_loop = 100
    return p
