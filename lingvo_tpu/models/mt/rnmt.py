"""RNMT+ : deep residual LSTM encoder-decoder with attention.

Re-designs the reference's RNN MT family (`lingvo/tasks/mt/encoder.py`
MTEncoderBiRNN and `decoder.py:MTDecoderV1` — stacked LSTMs, first-layer
bidirectional encoder, per-step additive attention feeding every decoder
layer, per-layer residuals; the RNMT+ recipe of arXiv:1804.09849). All
recurrence runs through `lax.scan` (core/recurrent), attention through the
seq_attention per-step API, and greedy decode is one compiled scan — no
per-step host round-trips.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import base_model
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import rnn_cell
from lingvo_tpu.core import rnn_layers
from lingvo_tpu.core import seq_attention
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.mt import model as mt_model


class RNMTEncoder(base_layer.BaseLayer):
  """Bidi first layer + residual unidirectional stack (ref MTEncoderBiRNN)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 32000, "Source vocab.")
    p.Define("model_dim", 512, "Output dim (and LSTM width).")
    p.Define("num_layers", 4, "Total LSTM layers (first is bidirectional).")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.model_dim
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=d, scale_sqrt_depth=True))
    cell = lambda i, o: rnn_cell.LSTMCellSimple.Params().Set(
        num_input_nodes=i, num_output_nodes=o)
    self.CreateChild(
        "bidi", rnn_layers.BidirectionalFRNN.Params().Set(
            fwd=cell(d, d // 2), bak=cell(d, d // 2)))
    for i in range(p.num_layers - 1):
      self.CreateChild(f"rnn_{i}",
                       rnn_layers.FRNN.Params().Set(cell=cell(d, d)))
    self.CreateChild("ln", layers_lib.LayerNorm.Params().Set(input_dim=d))

  def FProp(self, theta, ids, paddings):
    p = self.p
    x = self.emb.EmbLookup(theta.emb, ids)
    x = self.bidi.FProp(self.ChildTheta(theta, "bidi"), x, paddings)
    for i in range(p.num_layers - 1):
      rnn = getattr(self, f"rnn_{i}")
      out, _ = rnn.FProp(self.ChildTheta(theta, f"rnn_{i}"), x, paddings)
      x = x + out  # residual (RNMT+ idiom)
    return self.ln.FProp(self.ChildTheta(theta, "ln"), x)


class RNMTDecoder(base_layer.BaseLayer):
  """Attention-fed residual LSTM decoder (ref MTDecoderV1 + RNMT+)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 32000, "Target vocab.")
    p.Define("model_dim", 512, "LSTM width (= encoder output dim).")
    p.Define("num_layers", 4, "LSTM layers (first carries the attention).")
    p.Define("atten_hidden_dim", 512, "Additive attention hidden dim.")
    p.Define("label_smoothing", 0.1, "Label smoothing.")
    p.Define("max_decode_len", 64, "Greedy decode budget.")
    p.Define("sos_id", 1, "Start token.")
    p.Define("eos_id", 2, "End token.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    d = p.model_dim
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=d, scale_sqrt_depth=True))
    atten = seq_attention.AdditiveAttention.Params().Set(
        source_dim=d, query_dim=d, hidden_dim=p.atten_hidden_dim)
    self.CreateChild(
        "frnn_atten",
        rnn_layers.FRNNWithAttention.Params().Set(
            cell=rnn_cell.LSTMCellSimple.Params().Set(
                num_input_nodes=d + d, num_output_nodes=d),
            attention=atten))
    for i in range(p.num_layers - 1):
      # context is concatenated to every layer's input (RNMT+)
      self.CreateChild(
          f"rnn_{i}",
          rnn_layers.FRNN.Params().Set(
              cell=rnn_cell.LSTMCellSimple.Params().Set(
                  num_input_nodes=d + d, num_output_nodes=d)))
    self.CreateChild(
        "softmax",
        layers_lib.SimpleFullSoftmax.Params().Set(
            input_dim=2 * d, num_classes=p.vocab_size))

  def _Stack(self, theta, encoder_out, src_paddings, target_ids,
             target_paddings):
    """Returns ([b, t, 2d] pre-softmax features, contexts)."""
    p = self.p
    x = self.emb.EmbLookup(theta.emb, target_ids)
    h, ctx, _ = self.frnn_atten.FProp(
        self.ChildTheta(theta, "frnn_atten"), encoder_out, src_paddings, x,
        target_paddings)
    for i in range(p.num_layers - 1):
      rnn = getattr(self, f"rnn_{i}")
      out, _ = rnn.FProp(
          self.ChildTheta(theta, f"rnn_{i}"),
          jnp.concatenate([h, ctx], axis=-1), target_paddings)
      h = h + out
    return jnp.concatenate([h, ctx], axis=-1)

  def FProp(self, theta, encoder_out, src_paddings, target_ids,
            target_paddings, target_labels):
    p = self.p
    feats = self._Stack(theta, encoder_out, src_paddings, target_ids,
                        target_paddings)
    xent = self.softmax.FProp(theta.softmax, feats, class_ids=target_labels,
                              label_smoothing=p.label_smoothing)
    weights = py_utils.SequenceMask(target_paddings)
    total_weight = jnp.maximum(jnp.sum(weights), 1e-8)
    avg = jnp.sum(xent.per_example_xent * weights) / total_weight
    return NestedMap(per_example_xent=xent.per_example_xent,
                     logits=xent.logits, avg_xent=avg,
                     total_weight=total_weight)

  def GreedyDecode(self, theta, encoder_out, src_paddings):
    """One compiled scan of stepwise cells + attention; returns
    NestedMap(topk_ids [b,1,T], topk_lens [b,1], topk_scores [b,1])."""
    p = self.p
    b, s, d = encoder_out.shape
    t_max = p.max_decode_len
    atten = self.frnn_atten.atten
    atten_theta = self.ChildTheta(theta, "frnn_atten").atten
    packed = atten.PackSource(atten_theta, encoder_out, src_paddings)

    cell0 = self.frnn_atten.cell
    cell0_theta = self.ChildTheta(theta, "frnn_atten").cell
    rest = [(getattr(self, f"rnn_{i}").cell,
             self.ChildTheta(theta, f"rnn_{i}").cell)
            for i in range(p.num_layers - 1)]

    state0 = NestedMap(
        ids=jnp.full((b,), p.sos_id, jnp.int32),
        done=jnp.zeros((b,), bool),
        score=jnp.zeros((b,), jnp.float32),
        lens=jnp.zeros((b,), jnp.int32),
        ctx=jnp.zeros((b, d), encoder_out.dtype),
        atten=atten.ZeroAttentionState(b, s),
        cell0=cell0.InitState(b),
        rest=[c.InitState(b) for c, _ in rest])

    def _Step(st, _):
      x = self.emb.EmbLookup(theta.emb, st.ids)
      cell0_state = cell0.FProp(
          cell0_theta, st.cell0, jnp.concatenate([x, st.ctx], -1))
      h = cell0.GetOutput(cell0_state)
      ctx, _, atten_state = atten.ComputeContextVector(
          atten_theta, packed, h, st.atten)
      ctx = ctx.astype(x.dtype)
      new_rest = []
      for (cell, ctheta), cstate in zip(rest, st.rest):
        cstate = cell.FProp(ctheta, cstate,
                            jnp.concatenate([h, ctx], -1))
        h = h + cell.GetOutput(cstate)
        new_rest.append(cstate)
      logits = self.softmax.Logits(
          theta.softmax, jnp.concatenate([h, ctx], -1)).astype(jnp.float32)
      nxt = jnp.argmax(logits, -1).astype(jnp.int32)
      logp = jax.nn.log_softmax(logits, -1)
      tok_score = jnp.take_along_axis(logp, nxt[:, None], -1)[:, 0]
      was_done = st.done
      new = NestedMap(
          ids=jnp.where(was_done, p.eos_id, nxt),
          done=was_done | (nxt == p.eos_id),
          score=st.score + jnp.where(was_done, 0.0, tok_score),
          lens=st.lens + (~was_done).astype(jnp.int32),
          ctx=ctx, atten=atten_state, cell0=cell0_state, rest=new_rest)
      return new, new.ids

    final, out_ids = jax.lax.scan(_Step, state0, None, length=t_max)
    out_ids = jnp.swapaxes(out_ids, 0, 1)                   # [b, t]
    return NestedMap(topk_ids=out_ids[:, None, :],
                     topk_lens=final.lens[:, None],
                     topk_scores=final.score[:, None])


class RNMTModel(mt_model.TransformerModel):
  """RNMT+ task: same loss/metrics plumbing, recurrent enc/dec, greedy
  decode (ref mt/model.py RNMTModel)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.encoder = RNMTEncoder.Params()
    p.decoder = RNMTDecoder.Params()
    return p

  def Decode(self, theta, input_batch):
    encoder_out = self.enc.FProp(theta.enc, input_batch.src.ids,
                                 input_batch.src.paddings)
    hyps = self.dec.GreedyDecode(theta.dec, encoder_out,
                                 input_batch.src.paddings)
    return NestedMap(
        topk_ids=hyps.topk_ids, topk_lens=hyps.topk_lens,
        topk_scores=hyps.topk_scores,
        target_labels=input_batch.tgt.labels,
        target_paddings=input_batch.tgt.paddings)

  def _DecodeEosId(self):
    return self.dec.p.eos_id
