"""MT task: encoder-decoder training + beam-search decode + BLEU.

Ref: lingvo/tasks/mt/model.py (TransformerModel): batch fields
src.{ids,paddings} tgt.{ids,labels,paddings}.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from lingvo_tpu.core import base_model
from lingvo_tpu.core import metrics as metrics_lib
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.mt import layers as mt_layers


class TransformerModel(base_model.BaseTask):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("encoder", mt_layers.TransformerEncoder.Params(), "Encoder.")
    p.Define("decoder", mt_layers.TransformerDecoder.Params(), "Decoder.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self.CreateChild("enc", self.p.encoder)
    self.CreateChild("dec", self.p.decoder)

  def ComputePredictions(self, theta, input_batch):
    encoder_out = self.enc.FProp(theta.enc, input_batch.src.ids,
                                 input_batch.src.paddings)
    dec_out = self.dec.FProp(
        theta.dec, encoder_out, input_batch.src.paddings,
        input_batch.tgt.ids, input_batch.tgt.paddings,
        input_batch.tgt.labels)
    return dec_out

  def ComputeLoss(self, theta, predictions, input_batch):
    metrics = NestedMap(
        loss=(predictions.avg_xent, predictions.total_weight),
        log_pplx=(predictions.avg_xent, predictions.total_weight))
    acc = jnp.sum(
        (jnp.argmax(predictions.logits, -1) == input_batch.tgt.labels) *
        (1.0 - input_batch.tgt.paddings)) / predictions.total_weight
    metrics.fraction_of_correct_next_step_preds = (acc,
                                                   predictions.total_weight)
    return metrics, NestedMap(xent=predictions.per_example_xent)

  def Inference(self):
    """'decode' subgraph: source ids -> beam-searched topk hypotheses
    (the all-XLA flat beam search jits into the exported StableHLO)."""
    import jax.numpy as jnp
    from lingvo_tpu.core import py_utils
    t = 16
    example = NestedMap(
        src=NestedMap(ids=jnp.zeros((1, t), jnp.int32),
                      paddings=jnp.zeros((1, t), jnp.float32)))

    def decode_fn(theta, inputs):
      with py_utils.EvalContext():
        encoder_out = self.enc.FProp(theta.enc, inputs.src.ids,
                                     inputs.src.paddings)
        hyps = self.dec.BeamSearchDecode(theta.dec, encoder_out,
                                         inputs.src.paddings)
      return NestedMap(topk_ids=hyps.topk_ids, topk_lens=hyps.topk_lens,
                       topk_scores=hyps.topk_scores)

    return {"decode": (decode_fn, example)}

  def Decode(self, theta, input_batch):
    encoder_out = self.enc.FProp(theta.enc, input_batch.src.ids,
                                 input_batch.src.paddings)
    hyps = self.dec.BeamSearchDecode(theta.dec, encoder_out,
                                     input_batch.src.paddings)
    return NestedMap(
        topk_ids=hyps.topk_ids, topk_lens=hyps.topk_lens,
        topk_scores=hyps.topk_scores,
        target_labels=input_batch.tgt.labels,
        target_paddings=input_batch.tgt.paddings)

  def CreateDecoderMetrics(self):
    return {
        "corpus_bleu": metrics_lib.CorpusBleuMetric(),
        "examples": metrics_lib.AverageMetric(),
    }

  def _DecodeEosId(self):
    """Eos id used to trim hyps/refs; decoder-family-specific."""
    return self.dec.p.beam_search.target_eos_id

  def PostProcessDecodeOut(self, decode_out, decoder_metrics):
    eos = self._DecodeEosId()
    best = np.asarray(decode_out.topk_ids[:, 0, :])
    lens = np.asarray(decode_out.topk_lens[:, 0])
    labels = np.asarray(decode_out.target_labels)
    pads = np.asarray(decode_out.target_paddings)
    for i in range(best.shape[0]):
      hyp = [str(t) for t in best[i, :lens[i]] if t != eos]
      ref_len = int((1.0 - pads[i]).sum())
      ref = [str(t) for t in labels[i, :ref_len] if t != eos]
      decoder_metrics["corpus_bleu"].Update(ref, hyp)
      decoder_metrics["examples"].Update(1.0)
