"""MT transformer encoder/decoder (ref: lingvo/tasks/mt/{encoder,decoder}.py).

Batch-major transformer enc-dec with beam-search decoding through the
KV-cache ExtendStep path (no host round trips, unlike the reference's C++
BeamSearchStep loop).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import base_layer
from lingvo_tpu.core import beam_search as beam_search_lib
from lingvo_tpu.core import layers as layers_lib
from lingvo_tpu.core import py_utils
from lingvo_tpu.core import transformer as transformer_lib
from lingvo_tpu.core.nested_map import NestedMap


class TransformerEncoder(base_layer.BaseLayer):
  """Embedding + positional + self-attention stack (ref mt/encoder.py)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 32000, "Source vocab.")
    p.Define("model_dim", 512, "Model dim.")
    p.Define("num_layers", 6, "Depth.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("hidden_dim", 2048, "FFN dim.")
    p.Define("input_dropout_prob", 0.0, "Dropout on embeddings.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.model_dim,
            scale_sqrt_depth=True))
    self.CreateChild(
        "pos_emb",
        layers_lib.PositionalEmbeddingLayer.Params().Set(
            embedding_dim=p.model_dim))
    tpl = transformer_lib.TransformerLayer.Params().Set(
        input_dim=p.model_dim, num_heads=p.num_heads, hidden_dim=p.hidden_dim,
        mask_self_atten=False)
    tpl.tr_atten_tpl.residual_dropout_prob = p.residual_dropout_prob
    tpl.tr_fflayer_tpl.residual_dropout_prob = p.residual_dropout_prob
    self.CreateChild(
        "stack",
        transformer_lib.StackedTransformerLayers.Params().Set(
            num_layers=p.num_layers, input_dim=p.model_dim,
            transformer_layer_params_tpl=tpl))
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def EmbedTokens(self, theta, ids):
    """[b, t] ids -> [b, t, d] token embeddings (no positional) — the
    crossover point for XEnDec-style embedding mixing."""
    return self.emb.EmbLookup(theta.emb, ids)

  def FPropEmb(self, theta, token_embs, paddings):
    """Runs the encoder from (possibly mixed) token embeddings."""
    p = self.p
    x = token_embs + self.pos_emb.FProp(
        NestedMap(), seq_length=token_embs.shape[1])[None].astype(
            token_embs.dtype)
    if p.input_dropout_prob > 0:
      x = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), x,
          keep_prob=1.0 - p.input_dropout_prob)
    return self.stack.FProp(theta.stack, x, paddings)

  def FProp(self, theta, ids, paddings):
    return self.FPropEmb(theta, self.EmbedTokens(theta, ids), paddings)


class TransformerDecoder(base_layer.BaseLayer):
  """Causal stack with cross-attention + softmax + beam search
  (ref mt/decoder.py)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("vocab_size", 32000, "Target vocab.")
    p.Define("model_dim", 512, "Model dim.")
    p.Define("num_layers", 6, "Depth.")
    p.Define("num_heads", 8, "Heads.")
    p.Define("hidden_dim", 2048, "FFN dim.")
    p.Define("label_smoothing", 0.1, "Label smoothing uncertainty.")
    p.Define("input_dropout_prob", 0.0, "Embedding dropout.")
    p.Define("residual_dropout_prob", 0.0, "Residual dropout.")
    p.Define("beam_search", beam_search_lib.BeamSearchHelper.Params(),
             "Beam search config.")
    return p

  def __init__(self, params):
    super().__init__(params)
    p = self.p
    self.CreateChild(
        "emb",
        layers_lib.SimpleEmbeddingLayer.Params().Set(
            vocab_size=p.vocab_size, embedding_dim=p.model_dim,
            scale_sqrt_depth=True))
    self.CreateChild(
        "pos_emb",
        layers_lib.PositionalEmbeddingLayer.Params().Set(
            embedding_dim=p.model_dim))
    tpl = transformer_lib.TransformerLayer.Params().Set(
        input_dim=p.model_dim, num_heads=p.num_heads, hidden_dim=p.hidden_dim,
        mask_self_atten=True, has_aux_atten=True)
    tpl.tr_atten_tpl.residual_dropout_prob = p.residual_dropout_prob
    tpl.tr_fflayer_tpl.residual_dropout_prob = p.residual_dropout_prob
    self.CreateChild(
        "stack",
        transformer_lib.StackedTransformerLayers.Params().Set(
            num_layers=p.num_layers, input_dim=p.model_dim,
            transformer_layer_params_tpl=tpl))
    self.CreateChild(
        "softmax",
        layers_lib.SimpleFullSoftmax.Params().Set(
            input_dim=p.model_dim, num_classes=p.vocab_size))
    self.CreateChild("dropout", layers_lib.DeterministicDropoutLayer.Params())

  def _PosDropout(self, theta, token_embs, position=None, seq_length=None):
    """Shared prologue: positional encoding + input dropout over token
    embeddings (used by id-input and mixed-embedding-input paths)."""
    if position is not None:
      pe = self.pos_emb.FProp(NestedMap(), position=position)
    else:
      pe = self.pos_emb.FProp(NestedMap(), seq_length=seq_length)[None]
    x = token_embs + pe.astype(token_embs.dtype)
    if self.p.input_dropout_prob > 0:
      x = self.dropout.FProp(
          self.ChildTheta(theta, "dropout"), x,
          keep_prob=1.0 - self.p.input_dropout_prob)
    return x

  def _Embed(self, theta, ids, position=None, seq_length=None):
    return self._PosDropout(theta, self.emb.EmbLookup(theta.emb, ids),
                            position=position, seq_length=seq_length)

  def EmbedTokens(self, theta, ids):
    """[b, t] ids -> [b, t, d] token embeddings (no positional)."""
    return self.emb.EmbLookup(theta.emb, ids)

  def FProp(self, theta, encoder_out, src_paddings, target_ids,
            target_paddings, target_labels):
    """Teacher-forced xent. Returns NestedMap(per_example_xent, logits,
    avg_xent, total_weight)."""
    p = self.p
    x = self._Embed(theta, target_ids, seq_length=target_ids.shape[1])
    x = self.stack.FProp(theta.stack, x, target_paddings,
                         aux_vecs=encoder_out, aux_paddings=src_paddings)
    xent = self.softmax.FProp(
        theta.softmax, x, class_ids=target_labels,
        label_smoothing=p.label_smoothing)
    weights = py_utils.SequenceMask(target_paddings)
    total_weight = jnp.maximum(jnp.sum(weights), 1e-8)
    avg = jnp.sum(xent.per_example_xent * weights) / total_weight
    return NestedMap(
        per_example_xent=xent.per_example_xent, logits=xent.logits,
        avg_xent=avg, total_weight=total_weight)

  def FPropMixture(self, theta, encoder_out, src_paddings, tgt_token_embs,
                   target_paddings, labels_pair, label_lambdas):
    """Crossover decode: mixed target-input embeddings + two-parent
    mixture labels (XEnDec F1/F2 loss; ref TransformerXDecoder).

    tgt_token_embs: [b, t, d] already-interpolated token embeddings;
    labels_pair: ([b, t] ids, [b, t] ids); label_lambdas: matching pair of
    [b, t] weights (summing to ~1 on valid positions). Returns
    NestedMap(avg_xent, total_weight).
    """
    x = self._PosDropout(theta, tgt_token_embs,
                         seq_length=tgt_token_embs.shape[1])
    x = self.stack.FProp(theta.stack, x, target_paddings,
                         aux_vecs=encoder_out, aux_paddings=src_paddings)
    logits = self.softmax.Logits(theta.softmax, x).astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    y0, y1 = labels_pair
    l0, l1 = label_lambdas
    lp0 = jnp.take_along_axis(logp, y0[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    lp1 = jnp.take_along_axis(logp, y1[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    l0 = l0.astype(jnp.float32)
    l1 = l1.astype(jnp.float32)
    total = jnp.maximum(jnp.sum(l0 + l1), 1e-8)
    avg = -jnp.sum(l0 * lp0 + l1 * lp1) / total
    return NestedMap(avg_xent=avg, total_weight=total)

  def BeamSearchDecode(self, theta, encoder_out, src_paddings):
    """Beam search over the KV-cache ExtendStep path."""
    p = self.p
    bs_p = p.beam_search
    b = encoder_out.shape[0]
    k = bs_p.num_hyps_per_beam
    t_max = bs_p.target_seq_len

    # tile encoder outputs over beams: [B*K, S, D]
    enc = jnp.repeat(encoder_out, k, axis=0)
    src_pad = jnp.repeat(src_paddings, k, axis=0)
    stack_states = self.stack.InitStates(theta.stack, b * k, t_max)
    init_states = NestedMap(stack=stack_states,
                            step=jnp.zeros((), jnp.int32))

    def _StepFn(states, ids_t):
      x = self._Embed(theta, ids_t,
                      position=states.step.astype(jnp.float32)[None, None])
      out, new_stack = self.stack.ExtendStep(
          theta.stack, x, states.stack, aux_vecs=enc, aux_paddings=src_pad)
      logits = self.softmax.Logits(theta.softmax, out)[:, 0, :]
      return logits, NestedMap(stack=new_stack, step=states.step + 1)

    helper = beam_search_lib.BeamSearchHelper(bs_p)
    return helper.Search(b, init_states, _StepFn)
