"""XEnDec: crossover encoder-decoder joint self-/supervised training.

Re-designs `lingvo/tasks/mt/model.py:401` TransformerXEnDecModel
(Cheng et al., ICML 2021, arXiv:2106.04060) TPU-first: the crossover pair
is the batch rolled by one (the reference's fallback when no separate
monolingual stream is wired), source embeddings are mixed under a
per-position Bernoulli mask, and the mixture-label target lambdas follow
the reference's attention-apportioned credit
(`model.py:420 _CreateTargetLambdas`): stop-gradient cross-attention probs
decide how much of each target position's loss belongs to each parent.
Everything is one jitted program — no Defuns, no graph surgery.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from lingvo_tpu.core import py_utils
from lingvo_tpu.core.nested_map import NestedMap
from lingvo_tpu.models.mt import model as mt_model


class TransformerXEnDecModel(mt_model.TransformerModel):
  """Transformer MT with the XEnDec crossover loss added in training."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("loss_clean_weight", 1.0, "Weight of the supervised loss.")
    p.Define("loss_mix_weight", 1.0, "Weight of the crossover (F1) loss.")
    p.Define("loss_mono_weight", 0.0,
             "Weight of the rolled-parent loss (ref loss_mono_weight; the "
             "roll fallback duplicates the clean loss, so default 0).")
    p.Define("crossover_prob", 0.5,
             "Bernoulli(source position comes from the OTHER parent).")
    p.Define("lambda_smooth", 0.0,
             "Additive smoothing of target lambdas before normalization.")
    return p

  # -- crossover machinery ---------------------------------------------------

  def _SourceMask(self, src_ids, step):
    """Deterministic per-step Bernoulli crossover mask [b, t] (1 = take
    the other parent's embedding)."""
    key = jax.random.fold_in(jax.random.PRNGKey(0x9E3779B9),
                             jnp.asarray(step, jnp.uint32))
    return jax.random.bernoulli(
        key, self.p.crossover_prob, src_ids.shape).astype(jnp.float32)

  def _TargetLambdas(self, atten_pair, src_lambdas_pair, src_pad_pair,
                     tgt_pad_pair):
    """Attention-apportioned target credit (ref _CreateTargetLambdas).

    atten_pair: two [b, tgt, src] stop-gradient cross-attention prob maps.
    Returns (input_lambdas, label_lambdas), each a pair of [b, tgt].
    """
    smooth = self.p.lambda_smooth
    a0 = jax.lax.stop_gradient(atten_pair[0])
    a1 = jax.lax.stop_gradient(atten_pair[1])
    l0 = jnp.sum(a0 * (src_lambdas_pair[0] *
                       (1.0 - src_pad_pair[0]))[:, None, :], -1)
    l0 = (l0 + smooth) * (1.0 - tgt_pad_pair[0])
    l1 = jnp.sum(a1 * (src_lambdas_pair[1] *
                       (1.0 - src_pad_pair[1]))[:, None, :], -1)
    l1 = (l1 + smooth) * (1.0 - tgt_pad_pair[1])
    # normalize EACH side (positions padded in both parents get (0, 0),
    # not (0, 1) — they carry no loss weight)
    denom = l0 + l1 + 1e-9
    label_lambdas = (l0 / denom, l1 / denom)
    # decoder INPUT at position t carries the previous label's credit
    input0 = jnp.pad(label_lambdas[0], ((0, 0), (1, 0)),
                     constant_values=1.0)[:, :-1]
    input_lambdas = (input0 * (1.0 - tgt_pad_pair[0]),
                     (1.0 - input0) * (1.0 - tgt_pad_pair[1]))
    return input_lambdas, label_lambdas

  def _CrossAttenProbs(self, collected):
    """Last decoder layer's cross-attention probs, head-averaged
    [b, tgt, src]."""
    assert collected, "no cross-attention probs collected"

    def _LayerIndex(path: str):
      # paths end in .../x_layers_<i>; numeric sort (lexicographic would
      # put x_layers_9 after x_layers_11)
      tail = path.rsplit("_", 1)[-1]
      return (int(tail) if tail.isdigit() else -1, path)

    last = collected[max(collected, key=_LayerIndex)]
    return jnp.mean(last.astype(jnp.float32), axis=1)

  # -- task hooks ------------------------------------------------------------

  def ComputePredictions(self, theta, input_batch):
    """Clean pass, collecting the decoder's cross-attention probs so the
    crossover loss doesn't pay a second clean forward."""
    with py_utils.NamedCollectionContext("cross_atten_probs") as coll:
      preds = super().ComputePredictions(theta, input_batch)
    preds.cross_atten = self._CrossAttenProbs(coll)
    return preds

  def ComputeLoss(self, theta, predictions, input_batch):
    p = self.p
    metrics, per_example = super().ComputeLoss(theta, predictions,
                                               input_batch)
    if py_utils.DoEval():
      return metrics, per_example

    clean_out, atten = predictions, predictions.cross_atten
    other = input_batch.Transform(lambda x: jnp.roll(x, 1, axis=0))
    other_atten = jnp.roll(atten, 1, axis=0)

    step = py_utils.GetGlobalStep()
    mask = self._SourceMask(input_batch.src.ids,
                            0 if step is None else step)
    src_pad = (input_batch.src.paddings.astype(jnp.float32),
               other.src.paddings.astype(jnp.float32))
    tgt_pad = (input_batch.tgt.paddings.astype(jnp.float32),
               other.tgt.paddings.astype(jnp.float32))
    # other side contributes where the mask picks it AND it's real; where
    # only the other parent is real, take it regardless of the mask (else
    # the position would be marked valid but carry a zero embedding)
    other_lambdas = jnp.where(
        (src_pad[0] > 0.5) & (src_pad[1] < 0.5), 1.0,
        mask * (1.0 - src_pad[1]))
    src_lambdas = ((1.0 - other_lambdas) * (1.0 - src_pad[0]),
                   other_lambdas)

    input_lambdas, label_lambdas = self._TargetLambdas(
        (atten, other_atten), src_lambdas, src_pad, tgt_pad)

    # mixed source through the encoder (the other parent IS the rolled
    # batch, so its embeddings are a batch-axis roll — no second gather)
    e_src = self.enc.EmbedTokens(theta.enc, input_batch.src.ids)
    e_src_other = jnp.roll(e_src, 1, axis=0)
    mix_src = (src_lambdas[0][..., None] * e_src +
               src_lambdas[1][..., None] * e_src_other)
    mix_src_pad = src_pad[0] * src_pad[1]  # valid if either parent is
    mix_enc = self.enc.FPropEmb(theta.enc, mix_src, mix_src_pad)

    # mixed target inputs + mixture labels through the decoder
    e_tgt = self.dec.EmbedTokens(theta.dec, input_batch.tgt.ids)
    e_tgt_other = jnp.roll(e_tgt, 1, axis=0)
    mix_tgt = (input_lambdas[0][..., None] * e_tgt +
               input_lambdas[1][..., None] * e_tgt_other)
    mix_tgt_pad = tgt_pad[0] * tgt_pad[1]
    mix_out = self.dec.FPropMixture(
        theta.dec, mix_enc, mix_src_pad, mix_tgt, mix_tgt_pad,
        (input_batch.tgt.labels, other.tgt.labels), label_lambdas)

    clean_loss = clean_out.avg_xent
    mix_loss = mix_out.avg_xent
    total = (p.loss_clean_weight * clean_loss +
             p.loss_mix_weight * mix_loss)
    if p.loss_mono_weight > 0:
      other_enc = self.enc.FProp(theta.enc, other.src.ids,
                                 other.src.paddings)
      mono = self.dec.FProp(theta.dec, other_enc, other.src.paddings,
                            other.tgt.ids, other.tgt.paddings,
                            other.tgt.labels)
      total = total + p.loss_mono_weight * mono.avg_xent
      metrics.mono_loss = (mono.avg_xent, mono.total_weight)

    w = clean_out.total_weight
    metrics.loss = (total, w)
    metrics.clean_loss = (clean_loss, w)
    metrics.mix_loss = (mix_loss, mix_out.total_weight)
    return metrics, per_example
