"""Synthetic MT input: a deterministic learnable 'translation'.

Ref shape contract: `tasks/mt/input_generator.py` NmtInput — batches with
src.{ids,paddings}, tgt.{ids,labels,paddings,weights}. The synthetic task
maps target = reversed(source) with a fixed token offset — forces real use of
encoder attention (reversal) while remaining quickly learnable.
"""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class TextMtInput(base_input_generator.FileBasedSequenceInputGenerator):
  """Real-data MT input: tab-separated "source<TAB>target" lines ->
  length-bucketed src/tgt batches (ref `tasks/mt/input_generator.py`
  NmtInput over `text_input.proto` records, bucketed by max side length).

  Source ids are eos-terminated (no sos); target follows the teacher-forcing
  layout (ids sos-prefixed, labels eos-suffixed).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("source_max_length", 64, "Max source tokens (incl eos).")
    p.Define("target_max_length", 64, "Max target tokens (incl sos/eos).")
    p.bucket_upper_bound = [16, 32, 64]
    p.bucket_batch_limit = [32, 16, 8]
    return p

  def ProcessRecord(self, record: bytes):
    p = self.p
    text = record.decode("utf-8", errors="replace").strip()
    if "\t" not in text:
      return None
    src_text, tgt_text = text.split("\t", 1)
    # source: [w..., eos] = the labels row of the tokenizer layout
    _, src_ids, src_pad = self.StringsToIds([src_text], p.source_max_length)
    tgt_ids, tgt_labels, tgt_pad = self.StringsToIds([tgt_text],
                                                     p.target_max_length)
    src_len = int((1.0 - src_pad[0]).sum())
    tgt_len = int((1.0 - tgt_pad[0]).sum())
    if src_len <= 1 or tgt_len <= 1:
      return None
    bound = max(src_len, tgt_len)
    return NestedMap(
        src=NestedMap(ids=src_ids[0][:src_len],
                      paddings=src_pad[0][:src_len]),
        tgt=NestedMap(ids=tgt_ids[0][:tgt_len],
                      labels=tgt_labels[0][:tgt_len],
                      paddings=tgt_pad[0][:tgt_len],
                      weights=np.ones(tgt_len, np.float32)),
        bucket_key=bound)


class SyntheticMtInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("src_seq_len", 16, "Max source length.")
    p.Define("tgt_seq_len", 18, "Max target length (incl SOS/EOS).")
    p.Define("vocab_size", 64, "Vocab (ids 3.. used for content).")
    p.Define("sos_id", 1, "SOS.")
    p.Define("eos_id", 2, "EOS.")
    p.Define("offset", 3, "Token mapping offset.")
    p.Define("reverse", False,
             "Reverse source order in the target (harder task).")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 104729 * self._step) % (2**31))
    self._step += 1
    b = p.batch_size
    src_ids = np.zeros((b, p.src_seq_len), np.int32)
    src_pad = np.ones((b, p.src_seq_len), np.float32)
    tgt_ids = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_labels = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_pad = np.ones((b, p.tgt_seq_len), np.float32)
    content = p.vocab_size - 3
    for i in range(b):
      n = rng.randint(3, p.src_seq_len + 1)
      src = rng.randint(0, content, n)
      src_ids[i, :n] = 3 + src
      src_pad[i, :n] = 0.0
      mapped = src[::-1] if p.reverse else src
      tgt = 3 + (mapped + p.offset) % content
      # tgt_ids = [SOS, tgt...]; labels = [tgt..., EOS]
      m = min(n + 1, p.tgt_seq_len)
      tgt_ids[i, 0] = p.sos_id
      tgt_ids[i, 1:m] = tgt[:m - 1]
      tgt_labels[i, :m - 1] = tgt[:m - 1]
      tgt_labels[i, m - 1] = p.eos_id
      tgt_pad[i, :m] = 0.0
    return NestedMap(
        src=NestedMap(ids=src_ids, paddings=src_pad),
        tgt=NestedMap(ids=tgt_ids, labels=tgt_labels, paddings=tgt_pad))
