"""Synthetic MT input: a deterministic learnable 'translation'.

Ref shape contract: `tasks/mt/input_generator.py` NmtInput — batches with
src.{ids,paddings}, tgt.{ids,labels,paddings,weights}. The synthetic task
maps target = reversed(source) with a fixed token offset — forces real use of
encoder attention (reversal) while remaining quickly learnable.
"""

from __future__ import annotations

import zlib

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap


class TextMtInput(base_input_generator.FileBasedSequenceInputGenerator):
  """Real-data MT input: tab-separated "source<TAB>target" lines ->
  length-bucketed src/tgt batches (ref `tasks/mt/input_generator.py`
  NmtInput over `text_input.proto` records, bucketed by max side length).

  Source ids are eos-terminated (no sos); target follows the teacher-forcing
  layout (ids sos-prefixed, labels eos-suffixed).
  """

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("source_max_length", 64, "Max source tokens (incl eos).")
    p.Define("target_max_length", 64, "Max target tokens (incl sos/eos).")
    p.bucket_upper_bound = [16, 32, 64]
    p.bucket_batch_limit = [32, 16, 8]
    return p

  def ProcessRecord(self, record: bytes):
    p = self.p
    text = record.decode("utf-8", errors="replace").strip()
    if "\t" not in text:
      return None
    src_text, tgt_text = text.split("\t", 1)
    # source: [w..., eos] = the labels row of the tokenizer layout
    _, src_ids, src_pad = self.StringsToIds([src_text], p.source_max_length)
    tgt_ids, tgt_labels, tgt_pad = self.StringsToIds([tgt_text],
                                                     p.target_max_length)
    src_len = int((1.0 - src_pad[0]).sum())
    tgt_len = int((1.0 - tgt_pad[0]).sum())
    if src_len <= 1 or tgt_len <= 1:
      return None
    bound = max(src_len, tgt_len)
    return NestedMap(
        src=NestedMap(ids=src_ids[0][:src_len],
                      paddings=src_pad[0][:src_len]),
        tgt=NestedMap(ids=tgt_ids[0][:tgt_len],
                      labels=tgt_labels[0][:tgt_len],
                      paddings=tgt_pad[0][:tgt_len],
                      weights=np.ones(tgt_len, np.float32)),
        bucket_key=bound)


class SyntheticMtInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("src_seq_len", 16, "Max source length.")
    p.Define("tgt_seq_len", 18, "Max target length (incl SOS/EOS).")
    p.Define("vocab_size", 64, "Vocab (ids 3.. used for content).")
    p.Define("sos_id", 1, "SOS.")
    p.Define("eos_id", 2, "EOS.")
    p.Define("offset", 3, "Token mapping offset.")
    p.Define("reverse", False,
             "Reverse source order in the target (harder task).")
    p.Define("strided", False,
             "Source sentences are strided arithmetic sequences (the "
             "SyntheticMassInput distribution) instead of iid tokens — "
             "models fine-tuning on the same text domain the MASS "
             "pretraining saw.")
    p.Define("num_strides", 3, "Stride range for strided=True.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 104729 * self._step) % (2**31))
    self._step += 1
    b = p.batch_size
    src_ids = np.zeros((b, p.src_seq_len), np.int32)
    src_pad = np.ones((b, p.src_seq_len), np.float32)
    tgt_ids = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_labels = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_pad = np.ones((b, p.tgt_seq_len), np.float32)
    content = p.vocab_size - 3
    for i in range(b):
      n = rng.randint(3, p.src_seq_len + 1)
      if p.strided:
        start = rng.randint(0, content)
        stride = rng.randint(1, p.num_strides + 1)
        src = (start + stride * np.arange(n)) % content
      else:
        src = rng.randint(0, content, n)
      src_ids[i, :n] = 3 + src
      src_pad[i, :n] = 0.0
      mapped = src[::-1] if p.reverse else src
      tgt = 3 + (mapped + p.offset) % content
      # tgt_ids = [SOS, tgt...]; labels = [tgt..., EOS]
      m = min(n + 1, p.tgt_seq_len)
      tgt_ids[i, 0] = p.sos_id
      tgt_ids[i, 1:m] = tgt[:m - 1]
      tgt_labels[i, :m - 1] = tgt[:m - 1]
      tgt_labels[i, m - 1] = p.eos_id
      tgt_pad[i, :m] = 0.0
    return NestedMap(
        src=NestedMap(ids=src_ids, paddings=src_pad),
        tgt=NestedMap(ids=tgt_ids, labels=tgt_labels, paddings=tgt_pad))


class SyntheticMassInput(base_input_generator.BaseInputGenerator):
  """Monolingual MASS pretraining batches (ref `core/ops/mass_op.cc` feeding
  `tasks/mt` MASS recipes): random content sentences through
  `core.mass.MassExample` — the encoder sees the sentence with a span
  masked, the decoder reconstructs the span (teacher-forced inside the
  span, loss weighted span-only via tgt.paddings)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("seq_len", 16, "Sentence length bound.")
    p.Define("vocab_size", 64, "Vocab; top id is the MASS mask token.")
    p.Define("mask_ratio", 0.5, "Masked span fraction.")
    p.Define("num_strides", 3,
             "Sentences are strided arithmetic token sequences with stride "
             "in [1, num_strides] — the masked span is then exactly "
             "reconstructable from context, so the reconstruction loss "
             "can approach zero (iid tokens would pin it at the entropy "
             "floor).")
    p.Define("seed", 0, "Seed.")
    return p

  @property
  def mask_id(self) -> int:
    return self.p.vocab_size - 1

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    from lingvo_tpu.core import mass
    p = self.p
    rng = np.random.RandomState((p.seed + 77447 * self._step) % (2 ** 31))
    self._step += 1
    b, t = p.batch_size, p.seq_len
    src_ids = np.zeros((b, t), np.int32)
    src_pad = np.ones((b, t), np.float32)
    tgt_ids = np.zeros((b, t), np.int32)
    tgt_labels = np.zeros((b, t), np.int32)
    tgt_pad = np.ones((b, t), np.float32)
    content = p.vocab_size - 4  # 0 pad, 1 sos, 2 eos, top mask
    for i in range(b):
      n = rng.randint(4, t + 1)
      start = rng.randint(0, content)
      stride = rng.randint(1, p.num_strides + 1)
      ids = 3 + (start + stride * np.arange(n)) % content
      ex = mass.MassExample(ids, self.mask_id,
                            seed=int(rng.randint(2 ** 31)),
                            mask_ratio=p.mask_ratio)
      src_ids[i, :n] = ex.src.ids
      src_pad[i, :n] = 0.0
      tgt_ids[i, :n] = ex.tgt.ids
      tgt_labels[i, :n] = ex.tgt.labels
      # span-only loss/attention: non-span decoder positions are padding
      tgt_pad[i, :n] = 1.0 - ex.tgt.weights
    return NestedMap(
        src=NestedMap(ids=src_ids, paddings=src_pad),
        tgt=NestedMap(ids=tgt_ids, labels=tgt_labels, paddings=tgt_pad))


class MassFileInput(base_input_generator.FileBasedSequenceInputGenerator):
  """File-backed MASS pretraining: monolingual text lines -> tokenized ->
  MassExample (the production path: native yielder + tokenizer + numpy
  MASS synthesis, = the reference's GenericInput + mass_op.cc chain)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("max_length", 64, "Max tokens per sentence.")
    p.Define("mask_ratio", 0.5, "Masked span fraction.")
    p.Define("mask_id", None,
             "Mask token id — MUST be an id the tokenizer never produces "
             "(reserve one in the vocab, as the reference's MASS recipes "
             "do). None auto-derives vocab_size - 1 for AsciiTokenizer "
             "only (its id space tops out at 73); other tokenizers "
             "require an explicit value.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._record_counter = 0
    p = self.p
    if p.mask_id is not None:
      self._mask_id = p.mask_id
    else:
      from lingvo_tpu.core import tokenizers
      if not isinstance(self.tokenizer, tokenizers.AsciiTokenizer):
        raise ValueError(
            "MassFileInput.mask_id must be set explicitly for "
            f"{type(self.tokenizer).__name__}: vocab_size - 1 is a real "
            "token there, and a colliding mask id silently corrupts the "
            "MASS signal.")
      self._mask_id = self.tokenizer.p.vocab_size - 1  # ascii ids end at 73

  def ProcessRecord(self, record: bytes):
    from lingvo_tpu.core import mass
    p = self.p
    text = record.decode("utf-8", errors="replace").strip()
    if not text:
      return None
    _, ids_row, pad_row = self.StringsToIds([text], p.max_length)
    n = int((1.0 - pad_row[0]).sum())
    if n <= 3:
      return None
    mask_id = self._mask_id
    # Stable digest + per-read counter: reproducible under a fixed p.seed
    # (python hash() is salted per process) while re-randomizing each
    # epoch's span like the reference mass_op.
    self._record_counter += 1
    seed = (zlib.crc32(record) ^ (p.seed * 2654435761) ^
            (self._record_counter * 40503)) & 0x7FFFFFFF
    ex = mass.MassExample(ids_row[0][:n], mask_id, seed=seed,
                          mask_ratio=p.mask_ratio)
    return NestedMap(
        src=NestedMap(ids=ex.src.ids, paddings=np.zeros(n, np.float32)),
        tgt=NestedMap(ids=ex.tgt.ids, labels=ex.tgt.labels,
                      paddings=(1.0 - ex.tgt.weights).astype(np.float32)),
        bucket_key=n)


class IdsMtInput(base_input_generator.FileBasedSequenceInputGenerator):
  """Pre-tokenized MT input: JSONL lines {"src": [ids...], "tgt": [ids...]}
  with eos-terminated sequences (the t2t translate-shard convention;
  `tools/t2t_to_jsonl.py` produces this from the reference's real WMT'14
  wordpiece shards). Target rows follow the teacher-forcing layout: ids
  sos-prefixed, labels eos-suffixed (ref `tasks/mt/input_generator.py`
  NmtInput target_id/target_label)."""

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("source_max_length", 64, "Max source tokens (incl eos).")
    p.Define("target_max_length", 64, "Max target tokens (incl sos/eos).")
    p.Define("sos_id", 0, "Teacher-forcing start id (t2t uses pad=0).")
    p.Define("drop_overlong", True,
             "Drop examples over the max lengths (False: truncate+eos).")
    p.bucket_upper_bound = [16, 32, 64]
    p.bucket_batch_limit = [32, 16, 8]
    return p

  def ProcessRecord(self, record: bytes):
    import json as _json
    p = self.p
    try:
      row = _json.loads(record.decode("utf-8"))
      src = [int(i) for i in row["src"]]
      tgt = [int(i) for i in row["tgt"]]
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
      return None
    if not src or not tgt:
      return None
    if len(src) > p.source_max_length or len(tgt) + 1 > p.target_max_length:
      if p.drop_overlong:
        return None
      eos = src[-1]
      src = src[:p.source_max_length - 1] + [eos]
      tgt = tgt[:p.target_max_length - 2] + [tgt[-1]]
    src_ids = np.asarray(src, np.int32)
    tgt_labels = np.asarray(tgt, np.int32)
    tgt_ids = np.asarray([p.sos_id] + tgt[:-1], np.int32)
    n_tgt = len(tgt)
    return NestedMap(
        src=NestedMap(ids=src_ids,
                      paddings=np.zeros(len(src), np.float32)),
        tgt=NestedMap(ids=tgt_ids, labels=tgt_labels,
                      paddings=np.zeros(n_tgt, np.float32),
                      weights=np.ones(n_tgt, np.float32)),
        bucket_key=max(len(src), n_tgt))
