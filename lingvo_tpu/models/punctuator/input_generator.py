"""Punctuator input: synthetic 'strip the punctuation' pairs (ref
`lingvo/tasks/punctuator/input_generator.py` over the Brown corpus:
source = lowercased unpunctuated text, target = original).

Token convention: content ids 5.., punctuation ids {3, 4} ('comma',
'period'); the source drops punctuation tokens, the target keeps them —
exactly the restoration task, fully learnable synthetically."""

from __future__ import annotations

import numpy as np

from lingvo_tpu.core import base_input_generator
from lingvo_tpu.core.nested_map import NestedMap

COMMA, PERIOD = 3, 4


class SyntheticPunctuatorInput(base_input_generator.BaseInputGenerator):

  @classmethod
  def Params(cls):
    p = super().Params()
    p.Define("src_seq_len", 20, "Max source tokens.")
    p.Define("tgt_seq_len", 26, "Max target tokens (incl sos/eos + punct).")
    p.Define("vocab_size", 64, "Vocab; content ids 5..")
    p.Define("sos_id", 1, "SOS.")
    p.Define("eos_id", 2, "EOS.")
    p.Define("clause_len", 4, "Tokens between punctuation marks.")
    p.Define("seed", 0, "Seed.")
    return p

  def __init__(self, params):
    super().__init__(params)
    self._step = 0

  def _InputBatch(self) -> NestedMap:
    p = self.p
    rng = np.random.RandomState((p.seed + 60013 * self._step) % (2**31))
    self._step += 1
    b = p.batch_size
    src_ids = np.zeros((b, p.src_seq_len), np.int32)
    src_pad = np.ones((b, p.src_seq_len), np.float32)
    tgt_ids = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_labels = np.zeros((b, p.tgt_seq_len), np.int32)
    tgt_pad = np.ones((b, p.tgt_seq_len), np.float32)
    for i in range(b):
      n = rng.randint(p.clause_len, p.src_seq_len + 1)
      content = rng.randint(5, p.vocab_size, n)
      # deterministic punctuation rule: comma after each clause, period at
      # the end — recoverable from position within the clause structure
      punctuated = []
      for j, tok in enumerate(content):
        punctuated.append(tok)
        if (j + 1) % p.clause_len == 0 and j + 1 < n:
          punctuated.append(COMMA)
      punctuated.append(PERIOD)
      punctuated = punctuated[:p.tgt_seq_len - 1]
      src_ids[i, :n] = content
      src_pad[i, :n] = 0.0
      m = len(punctuated)  # <= tgt_seq_len - 1 by the truncation above
      tgt_ids[i, 0] = p.sos_id
      tgt_ids[i, 1:m + 1] = punctuated
      tgt_labels[i, :m] = punctuated
      tgt_labels[i, m] = p.eos_id
      tgt_pad[i, :m + 1] = 0.0
    return NestedMap(
        src=NestedMap(ids=src_ids, paddings=src_pad),
        tgt=NestedMap(ids=tgt_ids, labels=tgt_labels, paddings=tgt_pad))
