"""Punctuator configs (ref `lingvo/tasks/punctuator/params/codelab.py`
RNMTModel — here the transformer seq2seq, which subsumes the RNMT recipe on
TPU)."""

from __future__ import annotations

from lingvo_tpu import model_registry
from lingvo_tpu.core import base_model_params
from lingvo_tpu.core import learner as learner_lib
from lingvo_tpu.core import optimizer as opt_lib
from lingvo_tpu.core import schedule as sched_lib
from lingvo_tpu.models.mt import model as mt_model
from lingvo_tpu.models.punctuator import input_generator


@model_registry.RegisterSingleTaskModel
class TransformerModel(base_model_params.SingleTaskModelParams):
  """Punctuation restoration as seq2seq translation."""

  BATCH_SIZE = 32
  VOCAB = 64
  MODEL_DIM = 128
  NUM_LAYERS = 4
  NUM_HEADS = 4
  HIDDEN_DIM = 512
  SRC_LEN = 20
  TGT_LEN = 26

  def Train(self):
    return input_generator.SyntheticPunctuatorInput.Params().Set(
        batch_size=self.BATCH_SIZE, vocab_size=self.VOCAB,
        src_seq_len=self.SRC_LEN, tgt_seq_len=self.TGT_LEN)

  def Test(self):
    return self.Train().Set(seed=99)

  def Task(self):
    p = mt_model.TransformerModel.Params()
    p.name = "punctuator"
    for enc_dec in (p.encoder, p.decoder):
      enc_dec.vocab_size = self.VOCAB
      enc_dec.model_dim = self.MODEL_DIM
      enc_dec.num_layers = self.NUM_LAYERS
      enc_dec.num_heads = self.NUM_HEADS
      enc_dec.hidden_dim = self.HIDDEN_DIM
    p.decoder.beam_search.target_seq_len = self.TGT_LEN
    p.train.learner = learner_lib.Learner.Params().Set(
        learning_rate=1e-3,
        optimizer=opt_lib.Adam.Params().Set(beta2=0.98),
        lr_schedule=sched_lib.Constant.Params(),
        clip_gradient_norm_to_value=1.0)
    p.train.tpu_steps_per_loop = 100
    return p


@model_registry.RegisterSingleTaskModel
class TransformerModelTiny(TransformerModel):
  """Smoke-test scale."""

  BATCH_SIZE = 8
  MODEL_DIM = 32
  NUM_LAYERS = 2
  NUM_HEADS = 2
  HIDDEN_DIM = 64
  SRC_LEN = 12
  TGT_LEN = 18
