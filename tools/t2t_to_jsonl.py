#!/usr/bin/env python
"""Converts tensor2tensor translate-style TFRecord shards (tf.Example with
int64 'inputs'/'targets' wordpiece-id lists) to the framework's JSONL MT
format, without a TensorFlow dependency.

The reference ships real WMT'14 en-de wordpiece data this tool consumes
(`/root/reference/lingvo/tasks/mt/testdata/translate_ende_wmt32k-train-*`,
`wmt14_ende_wpm_32k_test.tfrecord`, + the 32k `.vocab`): TFRecord framing is
[u64 length][u32 crc][payload][u32 crc]; the payload is a tf.Example proto
parsed here with a minimal varint walker (wire format only — no generated
code).

Usage:
  python tools/t2t_to_jsonl.py IN.tfrecord OUT.jsonl [--vocab=V --text]
Each output line: {"src": [ids...], "tgt": [ids...]} (+"src_text"/"tgt_text"
detokenized via the wordpiece vocab when --vocab is given).
"""

from __future__ import annotations

import json
import struct
import sys


def ReadTfRecords(path: str):
  """Yields raw record payloads from a TFRecord file (crc not verified)."""
  with open(path, "rb") as f:
    while True:
      header = f.read(12)
      if len(header) < 12:
        return
      (length,) = struct.unpack("<Q", header[:8])
      payload = f.read(length)
      if len(payload) < length:
        return
      f.read(4)  # payload crc
      yield payload


def _ReadVarint(buf: bytes, pos: int):
  result = 0
  shift = 0
  while True:
    b = buf[pos]
    pos += 1
    result |= (b & 0x7F) << shift
    if not b & 0x80:
      return result, pos
    shift += 7


def _WalkFields(buf: bytes):
  """Yields (field_number, wire_type, value) over a proto message buffer.
  value: int for varint/fixed, bytes for length-delimited."""
  pos = 0
  n = len(buf)
  while pos < n:
    tag, pos = _ReadVarint(buf, pos)
    field, wire = tag >> 3, tag & 7
    if wire == 0:
      val, pos = _ReadVarint(buf, pos)
    elif wire == 2:
      ln, pos = _ReadVarint(buf, pos)
      val = buf[pos:pos + ln]
      pos += ln
    elif wire == 5:
      val = struct.unpack("<I", buf[pos:pos + 4])[0]
      pos += 4
    elif wire == 1:
      val = struct.unpack("<Q", buf[pos:pos + 8])[0]
      pos += 8
    else:
      raise ValueError(f"unsupported wire type {wire}")
    yield field, wire, val


def _Int64List(buf: bytes):
  """Int64List message -> list of ints (field 1, packed or repeated)."""
  out = []
  for field, wire, val in _WalkFields(buf):
    if field != 1:
      continue
    if wire == 2:  # packed
      pos = 0
      while pos < len(val):
        v, pos = _ReadVarint(val, pos)
        out.append(v)
    else:
      out.append(val)
  return out


def ParseExample(payload: bytes) -> dict:
  """tf.Example -> {feature_name: [int64...]} (int64 features only)."""
  features = {}
  for field, _, val in _WalkFields(payload):        # Example
    if field != 1:
      continue
    for f2, _, entry in _WalkFields(val):           # Features.feature map
      if f2 != 1:
        continue
      key, ints = None, None
      for f3, _, v3 in _WalkFields(entry):          # map entry
        if f3 == 1:
          key = v3.decode("utf-8")
        elif f3 == 2:
          for f4, _, v4 in _WalkFields(v3):         # Feature
            if f4 == 3:                             # int64_list
              ints = _Int64List(v4)
      if key is not None and ints is not None:
        features[key] = ints
  return features


def LoadWordpieceVocab(path: str):
  """'piece<TAB>score' lines -> id->piece list (line order = id)."""
  pieces = []
  with open(path, encoding="utf-8") as f:
    for line in f:
      pieces.append(line.rstrip("\n").split("\t")[0])
  return pieces


def IdsToText(ids, pieces) -> str:
  """Wordpiece detokenization: '▁' marks a word start (space)."""
  toks = []
  for i in ids:
    if 0 <= i < len(pieces):
      p = pieces[i]
      if p in ("<s>", "</s>", "<unk>", "<pad>"):
        continue
      toks.append(p)
  return "".join(toks).replace("▁", " ").strip()


def main():
  args = [a for a in sys.argv[1:] if not a.startswith("--")]
  opts = dict(a[2:].split("=", 1) if "=" in a else (a[2:], "1")
              for a in sys.argv[1:] if a.startswith("--"))
  in_path, out_path = args
  pieces = LoadWordpieceVocab(opts["vocab"]) if "vocab" in opts else None
  n = 0
  with open(out_path, "w") as out:
    for payload in ReadTfRecords(in_path):
      ex = ParseExample(payload)
      # t2t naming ('inputs'/'targets') or lingvo NmtInput naming
      # ('source_id'/'target_label', ref input_generator.NmtInput)
      src = ex.get("inputs", ex.get("source_id"))
      tgt = ex.get("targets", ex.get("target_label"))
      if src is None or tgt is None:
        continue
      row = {"src": src, "tgt": tgt}
      if pieces and opts.get("text"):
        row["src_text"] = IdsToText(ex["inputs"], pieces)
        row["tgt_text"] = IdsToText(ex["targets"], pieces)
      out.write(json.dumps(row) + "\n")
      n += 1
  print(f"wrote {n} examples to {out_path}", file=sys.stderr)


if __name__ == "__main__":
  main()
