#!/usr/bin/env python
"""Ragged-step ladder: per-step prefill token budget sweep.

Plays bench.py's seeded mixed-length greedy streams (byte-identity
asserted inside the bench) against the one-program ragged engine at a
ladder of `prefill_token_budget` values — the single knob the unified
step exposes (docs/ragged_step.md): the packed width is
max_batch * (k + 1) + budget, so a bigger budget buys prefill
throughput with a wider (slower) step while decode rows keep their
mandatory lanes either way. Each rung replays BOTH variance arms
against the padded three-program legacy baseline, so the ladder shows
where the waste and throughput ratios peak for a given stream shape.

One JSON line per rung with the bench's full arm breakdown
(tokens_per_sec_ratio, waste_per_step_ratio, decode_p99_ms per mode)
plus the acceptance booleans.

Usage: python tools/ragged_sweep.py [budget ...]   (default: chunk x {1,2,4})
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def main():
  bench._EnsureBackend()
  import jax
  import jax.numpy as jnp
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401

  on_tpu = jax.devices()[0].platform != "cpu"
  chunk = 64 if on_tpu else 8
  budgets = [int(a) for a in sys.argv[1:]] or [chunk, 2 * chunk, 4 * chunk]
  for b in budgets:
    res = bench._BenchRaggedStep(jax, jnp, model_registry, on_tpu, budget=b)
    print(json.dumps({"variant": f"budget-{b}", **res}), flush=True)


if __name__ == "__main__":
  main()
