#!/usr/bin/env python
"""Speculative-decoding ladder: draft source x tree width w x depth k.

Plays bench.py's seeded Poisson serving stream (greedy, byte-identity
asserted inside the bench) against spec-decode engines over the grid

    w in {1, 2, 4}  x  k in {2, 4, 8}  x  draft in {self, model}

where `self` is 1-layer early-exit self-speculation over the target's
own theta, `model` is an independent tiny pageless SSM draft
(docs/speculative_decoding.md), and w > 1 submits a token TREE of w
root-anchored branches per speculating row (w == 1 is chain
speculation, bitwise the pre-tree engine). One JSON line per variant
with tokens_per_sec_speedup, acceptance_rate, the accepted-length AND
accepted-depth histograms, and branch / width-clamp counters — the grid
shows the acceptance/verify-width trade directly: extra siblings only
pay while the target actually forks where the draft hedges, and extra
depth only while the draft keeps matching. (Acceptance between two
random-init models skews unrealistically high — both collapse to
last-token echo — so read the speedups as machinery cost at a GIVEN
acceptance, not as what a distilled draft would deliver.)

The shared baseline (the plain engine on the same stream) is measured
once and echoed first.

Usage: python tools/spec_sweep.py [k ...]        (default: 2 4 8)
       SPEC_SWEEP_WS=1,2 python tools/spec_sweep.py
       SPEC_SWEEP_DRAFTS=self python tools/spec_sweep.py
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import bench  # noqa: E402


def main():
  bench._EnsureBackend()
  import jax
  import jax.numpy as jnp
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401

  on_tpu = jax.devices()[0].platform != "cpu"
  ks = [int(a) for a in sys.argv[1:]] or [2, 4, 8]
  ws = [int(w) for w in
        os.environ.get("SPEC_SWEEP_WS", "1,2,4").split(",")]
  drafts = os.environ.get("SPEC_SWEEP_DRAFTS", "self,model").split(",")
  grid = [(d, k, w) for w in ws for k in ks for d in drafts]
  res = bench._BenchSpecDecode(jax, jnp, model_registry, on_tpu,
                               variants=grid)
  base = {k: v for k, v in res.items() if k != "variants"}
  print(json.dumps({"variant": "baseline", **base}), flush=True)
  for v in res["variants"]:
    print(json.dumps(
        {"variant": f"{v['draft']}-w{v['w']}-k{v['k']}", **v}),
        flush=True)


if __name__ == "__main__":
  main()
