#!/usr/bin/env python
"""Sequence-mixer sweep: what does O(1) decode state actually buy?

Sweeps the three stack shapes from docs/sequence_mixers.md — pure
attention, pure SSM, and the hybrid (attention every Nth layer) — across
sequence lengths 1k-32k and prints one JSON line per variant with:

  - decode_state_bytes_per_seq at each length (via jax.eval_shape, so the
    32k points cost nothing even on a CPU host). The acceptance bar: the
    SSM curve is FLAT, the attention curve is linear, the hybrid grows at
    attention_share/num_layers of the attention slope.
  - slots_at_hbm_budget: how many concurrent sequences fit a fixed decode
    HBM budget (the budget = what `slots` attention sequences need at
    `budget_seq_len`) — the more-concurrent-requests-at-fixed-HBM claim.
  - measured decode throughput (chunked Prefill + greedy ExtendStep scan)
    at a length the host can actually run.

Usage: python tools/mixer_sweep.py [variant ...]
Variants: attention ssm hybrid (default: all three)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import bench  # noqa: E402

SEQ_LADDER = (1024, 2048, 4096, 8192, 16384, 32768)

# mixer_atten_every_n per variant: 1 = attention at every layer (the plain
# stack), 0 = pure SSM, and the recipe's own spacing for the hybrid
VARIANTS = {"attention": 1, "ssm": 0, "hybrid": None}


def _Build(jax, jnp, model_registry, every_n):
  on_cpu = jax.devices()[0].platform == "cpu"
  name = ("lm.synthetic_packed_input.DenseLmSsmHybridTiny" if on_cpu else
          "lm.synthetic_packed_input.DenseLmSsmHybrid")
  mp = model_registry.GetParams(name, "Train")
  mp.task.input = mp.input
  if every_n is not None:
    mp.task.mixer_atten_every_n = every_n
  task = mp.task.Instantiate()
  task.FinalizePaths()
  return mp, task


def _StateBytesPerSeq(jax, task, theta, max_len, b=4):
  """Decode-state bytes for one sequence at max_len — abstract eval only,
  nothing is allocated (the 32k attention point would be real HBM)."""
  states = jax.eval_shape(lambda th: task.InitDecodeState(th, b, max_len),
                          theta)
  total = sum(int(np.prod(x.shape)) * x.dtype.itemsize
              for x in jax.tree_util.tree_leaves(states)
              if hasattr(x, "shape"))
  return total // b


def _DecodeTps(jax, jnp, task, theta, on_tpu):
  """Measured decode throughput: chunked Prefill + greedy ExtendStep scan
  (the GShardDecode hot loop, minus host I/O)."""
  b = 4
  p_len, steps = (256, 256) if on_tpu else (16, 32)
  total = p_len + steps
  prompts = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1,
                               task.p.vocab_size)

  @jax.jit
  def run(theta, prompts):
    states = task.InitDecodeState(theta, b, total)
    logits, states = task.Prefill(theta, prompts, states, live_len=p_len)

    def _Sample(carry, _):
      states, lg = carry
      nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
      nl, states = task.ExtendStep(theta, nxt[:, None], states)
      return (states, nl), nxt

    (_, _), out = jax.lax.scan(_Sample, (states, logits[:, -1, :]), None,
                               length=steps)
    return out

  reps = (2, 6) if on_tpu else (2, 6)
  t = bench._MarginalStepTime(lambda _: run(theta, prompts),
                              lambda out: float(jnp.sum(out)), *reps)
  return {
      "prompt_len": p_len, "decode_steps": steps, "batch": b,
      "wall_ms": round(t * 1e3, 2),
      "tokens_per_sec": round(b * steps / t, 1),
  }


def _Measure(jax, jnp, model_registry, name, every_n,
             slots=8, budget_seq_len=8192):
  mp, task = _Build(jax, jnp, model_registry, every_n)
  on_tpu = jax.devices()[0].platform != "cpu"
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))

  ladder = {str(s): _StateBytesPerSeq(jax, task, theta, s)
            for s in SEQ_LADDER}
  lo, hi = ladder[str(SEQ_LADDER[0])], ladder[str(SEQ_LADDER[-1])]

  res = {
      "every_n": task.p.mixer_atten_every_n if every_n is None else every_n,
      "decode_state_bytes_per_seq": ladder,
      "state_growth_1k_to_32k": round(hi / max(lo, 1), 2),
      "state_flat": hi == lo,
      "decode": _DecodeTps(jax, jnp, task, theta, on_tpu),
  }
  # fixed-HBM admission: budget = `slots` ATTENTION sequences at
  # budget_seq_len; how many of THIS variant's sequences fit the same HBM
  _, atten_task = _Build(jax, jnp, model_registry, VARIANTS["attention"])
  atten_theta = jax.eval_shape(
      lambda k: atten_task.InstantiateVariables(k), jax.random.PRNGKey(0))
  budget = slots * _StateBytesPerSeq(jax, atten_task, atten_theta,
                                     budget_seq_len)
  mine = _StateBytesPerSeq(jax, task, theta, budget_seq_len)
  res["slots_at_hbm_budget"] = {
      "budget_seq_len": budget_seq_len,
      "budget_bytes": budget,
      "attention_slots": slots,
      "slots": int(budget // max(mine, 1)),
  }
  del name
  return res


def main():
  bench._EnsureBackend()
  import gc
  import jax
  import jax.numpy as jnp
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401
  names = sys.argv[1:] or list(VARIANTS)
  for name in names:
    try:
      res = _Measure(jax, jnp, model_registry, name, VARIANTS[name])
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({"variant": name, **res}), flush=True)
    gc.collect()


if __name__ == "__main__":
  main()
