#!/usr/bin/env python
"""AOT-lowers the flagship scale configs against their intended mesh shapes
on a virtual CPU device topology and reports collectives + per-device HBM.

VERDICT r2 Next #2: DenseLm8B / DenseLm175B / MoELm64E exist as configs but
were never compiled against a big mesh — exactly where GSPMD surprises
(accidental all-gathers, per-device OOM) live. This tool force-creates
N fake CPU devices (XLA_FLAGS=--xla_force_host_platform_device_count=N),
jit-lowers the FULL TrainStep with the production shardings, runs the XLA
SPMD partitioner via .compile(), and reports:
  - collective ops present in the optimized HLO (all-to-all vs all-gather
    on the MoE dispatch path),
  - XLA's per-device memory estimate vs the target chip's HBM.

Run one config per process (device count is fixed at jax init):
  python tools/scale_lowering.py DenseLm8B
Prints one JSON line; `__graft_entry__.dryrun_multichip` shells out to this
for its scale-lowering report.
"""

from __future__ import annotations

import collections
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# (config, mesh axes, target chip HBM bytes, chip name) — mesh sizes follow
# the reference's intended topologies (synthetic_packed_input.py:161-288)
# adapted to named axes; HBM targets: v3 16G (8B/175B per the ref README),
# v5p 95G for the MoE north star.
CONFIGS = {
    "DenseLm8B": dict(model="lm.synthetic_packed_input.DenseLm8B",
                      mesh={"data": 4, "model": 8},
                      hbm=16e9, chip="v3 (16G)"),
    # model=32 alone leaves 104.8G/device (f32 master + momentum replicated
    # over the data axis); ZeRO/FSDP-sharding the train state over 'data'
    # brings it under the chip. (64-way model sharding is worse: 96 heads
    # don't divide 64, so attention weights fall back to replicated.)
    "DenseLm175B": dict(model="lm.synthetic_packed_input.DenseLm175B",
                        mesh={"data": 4, "model": 32}, fsdp="data",
                        hbm=95e9, chip="v5p (95G)"),
    "MoELm64E": dict(model="lm.synthetic_packed_input.MoELm64E",
                     mesh={"data": 2, "expert": 32, "model": 2},
                     hbm=95e9, chip="v5p (95G)"),
}


def _Setup(n_devices: int):
  flags = os.environ.get("XLA_FLAGS", "")
  os.environ["XLA_FLAGS"] = (
      f"{flags} --xla_force_host_platform_device_count={n_devices}")
  os.environ["JAX_PLATFORMS"] = "cpu"
  # A sitecustomize may have imported jax and registered a tunneled TPU
  # plugin already; re-point the not-yet-initialized backend at CPU and
  # drop non-cpu factories (same recipe as tests/conftest.py / bench.py).
  import jax
  try:
    import chex  # noqa: F401
  except ImportError:
    pass
  try:
    import jax.experimental.pallas  # noqa: F401
    import jax.experimental.pallas.tpu  # noqa: F401
  except ImportError:
    pass
  from jax._src import xla_bridge
  jax.config.update("jax_platforms", "cpu")
  for name in list(getattr(xla_bridge, "_backend_factories", {})):
    if name not in ("cpu", "interpreter"):
      xla_bridge._backend_factories.pop(name, None)


def Run(name: str) -> dict:
  cfg = CONFIGS[name]
  import numpy as np
  import jax
  import jax.numpy as jnp
  from lingvo_tpu import model_registry
  from lingvo_tpu.parallel import mesh as mesh_lib
  import lingvo_tpu.models.all_params  # noqa: F401

  n = int(np.prod(list(cfg["mesh"].values())))
  assert len(jax.devices()) >= n, (len(jax.devices()), n)
  mesh = mesh_lib.MakeMesh(cfg["mesh"], devices=jax.devices()[:n])

  mp = model_registry.GetParams(cfg["model"], "Train")
  mp.task.input = mp.input
  # Global batch = per-host batch x data-axis size (how the multi-host
  # executor feeds it); shapes matter for lowering, values never exist.
  mp.task.input.batch_size = max(
      mp.task.input.batch_size * cfg["mesh"].get("data", 1), 2)
  task = mp.task.Instantiate()
  task.FinalizePaths()

  # Abstract state/batch: eval_shape builds the full pytree without
  # materializing a single weight.
  state_shape = jax.eval_shape(
      lambda k: task.CreateTrainState(k), jax.random.PRNGKey(0))
  gen = mp.input.Instantiate()
  batch = gen.GetPreprocessedInputBatch()
  batch_shape = jax.tree_util.tree_map(
      lambda x: jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype), batch)

  state_sh = mesh_lib.TrainStateShardings(mesh, task, state_shape,
                                          fsdp_axis=cfg.get("fsdp"))
  data_ax = "data" if "data" in cfg["mesh"] else None
  batch_sh = jax.tree_util.tree_map(
      lambda x: jax.sharding.NamedSharding(
          mesh, jax.sharding.PartitionSpec(
              *([data_ax] if np.ndim(x) else []))), batch_shape)

  import time
  with mesh_lib.MeshContext(mesh):
    t0 = time.time()
    lowered = jax.jit(
        task.TrainStep, donate_argnums=(0,),
        in_shardings=(state_sh, batch_sh)).lower(state_shape, batch_shape)
    t_lower = time.time() - t0
    t0 = time.time()
    compiled = lowered.compile()
    t_compile = time.time() - t0

  hlo = compiled.as_text()
  dump = os.environ.get("SCALE_HLO_DUMP")
  if dump:
    with open(dump, "w") as f:
      f.write(hlo)
  # Proper instruction-level counting via the attribution parser — a raw
  # text regex counts each defining line twice plus every operand use
  # (the r04 reports said "204 all-to-alls" for a program with 6).
  import collective_attribution
  attr = collective_attribution.Analyze(hlo)
  colls = collections.Counter(attr["instructions"])
  mem = compiled.memory_analysis()
  per_dev = {
      "output_bytes_gb": round(mem.output_size_in_bytes / 1e9, 2),
      "temp_bytes_gb": round(mem.temp_size_in_bytes / 1e9, 2),
      "argument_bytes_gb": round(mem.argument_size_in_bytes / 1e9, 2),
  }
  # arguments alias donated outputs; peak ~= args + temps
  peak = mem.argument_size_in_bytes + mem.temp_size_in_bytes
  n_params = sum(
      int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(
          state_shape.theta))
  result = {
      "config": name,
      "mesh": cfg["mesh"],
      "devices": n,
      "params_b": round(n_params / 1e9, 2),
      "collectives": dict(colls),
      "collectives_executed_per_step": attr["executed_per_step"],
      "collective_mb_per_step": {
          k: round(v / 1e6, 1) for k, v in attr["bytes_per_step"].items()},
      "per_device": per_dev,
      "per_device_peak_gb": round(peak / 1e9, 2),
      "target_chip": cfg["chip"],
      "fits_target_hbm": bool(peak <= cfg["hbm"]),
      "lower_s": round(t_lower, 1),
      "compile_s": round(t_compile, 1),
  }
  if name == "MoELm64E":
    # the dispatch path must ride all-to-all, not all-gather
    result["dispatch_all_to_all"] = colls.get("all-to-all", 0) > 0
  return result


def main():
  name = sys.argv[1]
  n = int(os.environ.get(
      "SCALE_DEVICES",
      __import__("numpy").prod(list(CONFIGS[name]["mesh"].values()))))
  _Setup(n)
  try:
    print(json.dumps(Run(name)), flush=True)
  except Exception as e:  # noqa: BLE001
    print(json.dumps({"config": name,
                      "error": f"{type(e).__name__}: {e}"[:400]}), flush=True)
    sys.exit(1)


if __name__ == "__main__":
  main()
