#!/usr/bin/env python
"""Diffs the Params trees of two registered models (ref
`lingvo/tools/compare_params.py`): prints keys present in only one and keys
whose values differ. Accepts registry names (`lm.one_billion_wds.X`) or
paths to `params.txt` files written into a logdir.

Usage: compare_params.py <model_or_file_a> <model_or_file_b> [--dataset=Train]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _LoadParamsText(spec: str, dataset: str) -> str:
  if os.path.exists(spec):
    with open(spec) as f:
      return f.read()
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401
  return model_registry.GetParams(spec, dataset).ToText()


def _ToDict(text: str) -> dict:
  out = {}
  for line in text.splitlines():
    line = line.strip()
    if not line or ":" not in line:
      continue
    key, val = line.split(":", 1)
    out[key.strip()] = val.strip()
  return out


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("a")
  ap.add_argument("b")
  ap.add_argument("--dataset", default="Train")
  args = ap.parse_args(argv)

  da = _ToDict(_LoadParamsText(args.a, args.dataset))
  db = _ToDict(_LoadParamsText(args.b, args.dataset))
  only_a = sorted(set(da) - set(db))
  only_b = sorted(set(db) - set(da))
  diff = sorted(k for k in set(da) & set(db) if da[k] != db[k])
  for k in only_a:
    print(f"< {k}: {da[k]}")
  for k in only_b:
    print(f"> {k}: {db[k]}")
  for k in diff:
    print(f"! {k}: {da[k]}  ->  {db[k]}")
  print(f"# {len(only_a)} only in A, {len(only_b)} only in B, "
        f"{len(diff)} differ, {len(set(da) & set(db)) - len(diff)} equal")
  return 0 if not (only_a or only_b or diff) else 1


if __name__ == "__main__":
  sys.exit(main())
