#!/usr/bin/env python
"""Prefix-cache ladder: what does cross-request KV sharing actually buy?

Sweeps share fraction x KV pool dtype over the same serving stack
(serving/engine.py + serving/prefix_cache.py) and prints one JSON line
per variant. Each variant plays an identical seeded Poisson request
stream — `share` of the requests open with one common system prompt —
against two engines at the SAME page pool, prefix cache ON vs OFF, and
reports:

  - prefill_tokens: prompt tokens actually computed by each engine (the
    engine's `prompt_tokens` counter). At share=0.9 the cache must cut
    this >= 2x; at share=0 the two engines should match (the cache costs
    nothing when nothing is shareable),
  - kv_page_peak: peak resident pages — the fixed-HBM footprint story,
  - slots_live_peak: peak admitted concurrency. The pool is sized below
    slots x per-request footprint, so sharing (borrowed pages are not
    charged to the pool) converts directly into admitted sequences,
  - streams_identical: greedy token streams byte-identical ON vs OFF
    within a variant — sharing may never shift a single token,
  - prefix_cache: the ON engine's hits/misses/hit_tokens/cow_copies/
    evictions counters (observe/schema.py PREFIX_CACHE_STATS_KEYS).

Usage: python tools/prefix_sweep.py [variant ...]
Variants: share0-bf16 share0-int8 share50-bf16 share50-int8
          share90-bf16 share90-int8 (default: all six)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import bench  # noqa: E402

# (share_fraction, kv_cache_dtype) per variant
VARIANTS = {
    "share0-bf16": (0.0, "bfloat16"),
    "share0-int8": (0.0, "int8"),
    "share50-bf16": (0.5, "bfloat16"),
    "share50-int8": (0.5, "int8"),
    "share90-bf16": (0.9, "bfloat16"),
    "share90-int8": (0.9, "int8"),
}


def _Build(jax):
  from lingvo_tpu.models.lm import layers as lm_layers
  on_cpu = jax.devices()[0].platform == "cpu"
  if on_cpu:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=128, model_dim=256, num_layers=2, num_heads=4,
        hidden_dim=512, use_rotary=True)
  else:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=32768, model_dim=1024, num_layers=8,
        num_heads=16, hidden_dim=4096, use_rotary=True)
  task = p.Instantiate()
  task.FinalizePaths()
  return task


def _Stream(rng, vocab, share, n_req, sys_len, t_lo, t_hi, o_lo, o_hi,
            mean_gap_s):
  """Seeded Poisson arrivals; `share` of the prompts open with one
  common system prompt (the sweep's independent variable)."""
  sys_prompt = rng.randint(1, vocab, sys_len).astype(np.int32)
  prompts = []
  for _ in range(n_req):
    tail = rng.randint(1, vocab, rng.randint(t_lo, t_hi + 1)).astype(
        np.int32)
    if rng.rand() < share:
      prompts.append(np.concatenate([sys_prompt, tail]))
    else:
      prompts.append(tail)
  max_news = rng.randint(o_lo, o_hi + 1, n_req)
  arrivals = np.concatenate(
      [[0.0], np.cumsum(rng.exponential(mean_gap_s, n_req - 1))])
  return sys_prompt, prompts, max_news, arrivals


def _Measure(jax, share, kv_cache_dtype):
  from lingvo_tpu.serving import engine as engine_lib
  on_tpu = jax.devices()[0].platform != "cpu"
  if on_tpu:
    n_req, b_slots, page, max_seq = 32, 8, 128, 1024
    sys_len, t_lo, t_hi, o_lo, o_hi = 256, 32, 128, 32, 128
  else:
    n_req, b_slots, page, max_seq = 12, 4, 8, 64
    sys_len, t_lo, t_hi, o_lo, o_hi = 32, 4, 14, 8, 16

  task = _Build(jax)
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  rng = np.random.RandomState(0)
  sys_prompt, prompts, max_news, arrivals = _Stream(
      rng, task.p.vocab_size, share, n_req, sys_len, t_lo, t_hi,
      o_lo, o_hi, mean_gap_s=0.005)

  # page-bound pool (half of slots x worst-case footprint): concurrency
  # is limited by pages, which is exactly what sharing relieves
  full_pages = -(-(sys_len + t_hi + o_hi) // page)
  num_pages = (b_slots * full_pages) // 2

  def _Play(prefix_cache):
    eng = engine_lib.ServingLoop(
        task, theta, page_size=page, num_pages=num_pages,
        max_batch=b_slots, max_seq_len=max_seq,
        prefill_chunk=16 if on_tpu else 4,
        kv_cache_dtype=kv_cache_dtype, prefix_cache=prefix_cache)
    # compile both programs + pre-warm the tree with the system prompt
    warm = sys_prompt[None, :]
    eng.RunBatch(warm, np.array([sys_len], np.int32), 4)
    eng.Start()
    t0 = time.perf_counter()
    handles = []
    for i in range(n_req):
      dt = t0 + arrivals[i] - time.perf_counter()
      if dt > 0:
        time.sleep(dt)
      handles.append(eng.Submit(prompts[i], int(max_news[i])))
    streams = [h.Result(timeout=1200) for h in handles]
    wall = time.perf_counter() - t0
    stats = eng.Stats()
    eng.Stop()
    return streams, wall, stats

  s_off, wall_off, stats_off = _Play(None)
  s_on, wall_on, stats_on = _Play(True)
  total_useful = int(np.sum(max_news))

  return {
      "share_fraction": share,
      "kv_cache_dtype": stats_on["kv_cache_dtype"],
      "requests": n_req,
      "slots": b_slots,
      "page_size": page,
      "num_pages": num_pages,
      "streams_identical": s_on == s_off,
      "prefill_tokens": {"off": stats_off["prompt_tokens"],
                         "on": stats_on["prompt_tokens"]},
      "prefill_tokens_ratio": round(
          stats_off["prompt_tokens"] / max(stats_on["prompt_tokens"], 1), 3),
      "kv_page_peak": {"off": stats_off["kv_pages"]["peak_in_use"],
                       "on": stats_on["kv_pages"]["peak_in_use"]},
      "slots_live_peak": {"off": stats_off["scheduler"]["slots_live_peak"],
                          "on": stats_on["scheduler"]["slots_live_peak"]},
      "prefix_cache": stats_on["prefix_cache"],
      "tokens_per_sec": {"off": round(total_useful / wall_off, 1),
                         "on": round(total_useful / wall_on, 1)},
  }


def main():
  bench._EnsureBackend()
  import gc
  import jax
  names = sys.argv[1:] or list(VARIANTS)
  for name in names:
    try:
      share, dtype = VARIANTS[name]
      res = _Measure(jax, share, dtype)
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({"variant": name, **res}), flush=True)
    gc.collect()


if __name__ == "__main__":
  main()
