#!/usr/bin/env python
"""Converts a reference (tensorflow/lingvo) TF checkpoint to a portable .npz
that `core.checkpointer.ImportNpzCheckpoint` can load (the SURVEY §7
checkpoint-compatibility story: self-format + converter, like the
reference's `keras2ckpt.py` direction).

Run this WHERE TENSORFLOW IS INSTALLED (the training image here is TF-free
by design); the output .npz needs only numpy to read.

  python tools/convert_tf_checkpoint.py \
    --tf_checkpoint=/ckpts/librispeech/ckpt-123456 \
    --output=/tmp/librispeech.npz \
    --strip_prefix=librispeech/ \
    --rules='enc\\.conv_(\\d+)\\.w=enc.convs.\\1.kernel'

Name mapping: TF variable names are first normalized (optional
--strip_prefix removed, trailing '/var' removed, '/' -> '.'), then each
--rules regex=template pair (';'-separated so regexes may contain commas,
matched against the NORMALIZED dotted name, first match wins) rewrites to
this framework's dotted theta path. Unmatched names pass through
normalized — run with --list first to see both columns.
"""

from __future__ import annotations

import argparse
import re
import sys

import numpy as np


def NormalizeName(name: str, strip_prefix: str = "") -> str:
  if strip_prefix and name.startswith(strip_prefix):
    name = name[len(strip_prefix):]
  for suffix in ("/var", "/.ATTRIBUTES/VARIABLE_VALUE"):
    if name.endswith(suffix):
      name = name[: -len(suffix)]
  return name.replace("/", ".")


def ApplyRules(name: str, rules) -> str:
  for pattern, template in rules:
    if re.fullmatch(pattern, name):
      return re.sub(pattern, template, name)
  return name


def IsModelVariable(name: str) -> bool:
  """True for model weights; False for optimizer slots / bookkeeping.

  lingvo TF1 names every model variable `<layer path>/<param>/var`, with
  optimizer slots as suffixes AFTER that (`.../var/Adam`, `.../var/Adam_1`,
  `.../var/Adafactor_1`) — so 'ends with /var' is the reliable model filter,
  not slot-name blacklists. TF2 object checkpoints use
  `.ATTRIBUTES/VARIABLE_VALUE` leaves, excluding `.OPTIMIZER_SLOT` paths.
  """
  if name.endswith("/var"):
    return True
  if name.endswith("/.ATTRIBUTES/VARIABLE_VALUE"):
    return ".OPTIMIZER_SLOT" not in name and "optimizer" not in name
  return False


def ParseRules(spec: str):
  rules = []
  # ';' separates pairs so regex bodies may contain ',' ({m,n}, [a,b])
  for pair in filter(None, spec.split(";")):
    if "=" not in pair:
      raise ValueError(f"rule {pair!r} is not regex=template")
    pattern, template = pair.split("=", 1)
    rules.append((pattern, template))
  return rules


def Convert(reader_items, output: str, strip_prefix: str, rules,
            dtype: str | None) -> int:
  """reader_items: iterable of (tf_name, numpy_array)."""
  out = {}
  for name, arr in reader_items:
    key = ApplyRules(NormalizeName(name, strip_prefix), rules)
    if key in out:
      raise ValueError(f"two TF variables map to {key!r}")
    if dtype:
      arr = arr.astype(dtype)
    out[key] = arr
  np.savez(output, **out)
  return len(out)


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--tf_checkpoint", required=True,
                  help="TF checkpoint prefix (the path before .index).")
  ap.add_argument("--output", help=".npz output path.")
  ap.add_argument("--strip_prefix", default="")
  ap.add_argument("--rules", default="",
                  help="';'-separated regex=template rewrites over the "
                  "normalized (dotted) names.")
  ap.add_argument("--dtype", default="",
                  help="cast all arrays (e.g. float32); default keeps.")
  ap.add_argument("--list", action="store_true",
                  help="print tf-name -> mapped-name -> shape and exit.")
  ap.add_argument("--keep_all", action="store_true",
                  help="also convert optimizer slots / bookkeeping vars "
                  "(default keeps only model weights: '.../var' in TF1 "
                  "naming, non-slot ATTRIBUTES leaves in TF2).")
  args = ap.parse_args(argv)

  try:
    import tensorflow as tf  # pytype: disable=import-error
  except ImportError:
    print("tensorflow is required to READ the checkpoint; run this tool in "
          "an environment with TF installed. (The output .npz is read with "
          "numpy only.)", file=sys.stderr)
    return 2

  reader = tf.train.load_checkpoint(args.tf_checkpoint)
  shape_map = reader.get_variable_to_shape_map()
  rules = ParseRules(args.rules)
  names = sorted(n for n in shape_map
                 if IsModelVariable(n) or args.keep_all)
  if args.list:
    for name in names:
      mapped = ApplyRules(NormalizeName(name, args.strip_prefix), rules)
      print(f"{name}\t{mapped}\t{shape_map[name]}")
    return 0
  if not args.output:
    print("--output is required unless --list", file=sys.stderr)
    return 2
  n = Convert(((name, reader.get_tensor(name)) for name in names),
              args.output, args.strip_prefix, rules, args.dtype or None)
  print(f"wrote {n} vars -> {args.output}")
  return 0


if __name__ == "__main__":
  sys.exit(main())
