#!/usr/bin/env python
"""KV-cache dtype ladder: what does an int8 KV cache actually buy?

Sweeps the KV pool storage dtype — fp32 / bf16 / int8 (with per-slot-
per-head f32 scale sidecars, docs/quantized_serving.md) — over the same
serving stack and prints one JSON line per variant with:

  - kv_bytes_per_token (scale sidecars included — the honest number, via
    quant.kv.StackKvCensus, the same census the serving engine prices its
    page pool with),
  - admitted_sequences at a fixed HBM budget (the budget = what `slots`
    fp32 sequences need at budget_seq_len). Acceptance bar: int8 admits
    >= 1.8x the sequences bf16 does at serving head dims,
  - measured decode tokens/sec through the dense-cache decode path
    (chunked Prefill + greedy ExtendStep scan with quantize-on-write /
    dequantize-on-read when int8),
  - score_delta_mean_abs: mean |delta| of teacher-forced next-token
    log-probs through the decode cache vs the fp32 variant — the decode-
    path ScoreSequences number (plain ScoreSequences never touches the KV
    cache, so the delta must be measured through ExtendStep).

Usage: python tools/kv_quant_sweep.py [variant ...]
Variants: fp32 bf16 int8 (default: all three)
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import bench  # noqa: E402

# kv_cache_dtype per variant (None = the layer's fprop dtype = fp32 here)
VARIANTS = {"fp32": None, "bf16": "bfloat16", "int8": "int8"}


def _Build(jax, kv_cache_dtype):
  """A serving-shaped LM at a serving head dim (the >= 1.8x bf16 -> int8
  admission claim needs dim_per_head >= 36; tiny test heads would hide it
  under the constant sidecar overhead)."""
  from lingvo_tpu.models.lm import layers as lm_layers
  on_cpu = jax.devices()[0].platform == "cpu"
  if on_cpu:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=128, model_dim=256, num_layers=2, num_heads=4,
        hidden_dim=512, use_rotary=True)
  else:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=32768, model_dim=1024, num_layers=8,
        num_heads=16, hidden_dim=4096, use_rotary=True)
  p.kv_cache_dtype = kv_cache_dtype
  task = p.Instantiate()
  task.FinalizePaths()
  return task


def _DecodeScore(jax, jnp, task, theta, ids):
  """Teacher-forced next-token log-probs THROUGH the decode cache:
  log P(ids[t+1] | ids[<=t]) from per-step ExtendStep logits. This is the
  ScoreSequences contract evaluated on the path KV quantization actually
  touches."""
  b, t = ids.shape

  @jax.jit
  def run(theta, ids):
    states = task.InitDecodeState(theta, b, t)

    def _Step(states, ids_t):
      logits, states = task.ExtendStep(theta, ids_t[:, None], states)
      return states, jax.nn.log_softmax(logits.astype(jnp.float32), -1)

    _, logps = jax.lax.scan(_Step, states, ids.swapaxes(0, 1))
    logps = logps.swapaxes(0, 1)                      # [B, T, V]
    return jnp.take_along_axis(logps[:, :-1], ids[:, 1:, None],
                               axis=-1)[..., 0]       # [B, T-1]

  return np.asarray(run(theta, ids))


def _DecodeTps(jax, jnp, task, theta, on_tpu):
  """Measured decode throughput (the GShardDecode hot loop, minus host
  I/O): quantize-on-write + dequantize-on-read ride inside ExtendStep when
  the cache is int8."""
  b = 4
  p_len, steps = (256, 256) if on_tpu else (16, 32)
  total = p_len + steps
  prompts = jax.random.randint(jax.random.PRNGKey(1), (b, p_len), 1,
                               task.p.vocab_size)

  @jax.jit
  def run(theta, prompts):
    states = task.InitDecodeState(theta, b, total)
    logits, states = task.Prefill(theta, prompts, states, live_len=p_len)

    def _Sample(carry, _):
      states, lg = carry
      nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
      nl, states = task.ExtendStep(theta, nxt[:, None], states)
      return (states, nl), nxt

    (_, _), out = jax.lax.scan(_Sample, (states, logits[:, -1, :]), None,
                               length=steps)
    return out

  t = bench._MarginalStepTime(lambda _: run(theta, prompts),
                              lambda out: float(jnp.sum(out)), 2, 6)
  return {
      "prompt_len": p_len, "decode_steps": steps, "batch": b,
      "wall_ms": round(t * 1e3, 2),
      "tokens_per_sec": round(b * steps / t, 1),
  }


def _Measure(jax, jnp, name, kv_cache_dtype, slots=8, budget_seq_len=4096):
  from lingvo_tpu.quant import kv as kv_quant
  task = _Build(jax, kv_cache_dtype)
  on_tpu = jax.devices()[0].platform != "cpu"
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))

  census = kv_quant.StackKvCensus(task)
  bpt = census["kv_bytes_per_token"]
  # fixed-HBM admission: the budget = `slots` FP32 sequences at
  # budget_seq_len; how many of THIS variant's sequences fit the same HBM
  fp32_task = _Build(jax, VARIANTS["fp32"])
  fp32_bpt = kv_quant.StackKvCensus(fp32_task)["kv_bytes_per_token"]
  budget = slots * budget_seq_len * fp32_bpt
  admitted = int(budget // (budget_seq_len * bpt))

  # decode-path score delta vs the fp32 variant (same theta, same ids)
  rng = np.random.RandomState(0)
  ids = jnp.asarray(rng.randint(1, task.p.vocab_size, size=(2, 24)),
                    jnp.int32)
  score = _DecodeScore(jax, jnp, task, theta, ids)
  score_f32 = _DecodeScore(jax, jnp, fp32_task, theta, ids)
  delta = float(np.mean(np.abs(score - score_f32)))

  res = {
      "kv_cache_dtype": census["kv_cache_dtype"],
      "kv_bytes_per_token": bpt,
      "kv_bytes_per_token_fp32": fp32_bpt,
      "compression_vs_fp32": round(fp32_bpt / bpt, 3),
      "admitted_sequences": {
          "budget_seq_len": budget_seq_len,
          "budget_bytes": budget,
          "fp32_sequences": slots,
          "sequences": admitted,
      },
      "score_delta_mean_abs": round(delta, 6),
      "decode": _DecodeTps(jax, jnp, task, theta, on_tpu),
  }
  del name
  return res


def main():
  bench._EnsureBackend()
  import gc
  import jax
  import jax.numpy as jnp
  names = sys.argv[1:] or list(VARIANTS)
  for name in names:
    try:
      res = _Measure(jax, jnp, name, VARIANTS[name])
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({"variant": name, **res}), flush=True)
    gc.collect()


if __name__ == "__main__":
  main()
