#!/usr/bin/env python
"""Scheduler ladder: what does SLO-aware admission actually buy?

Sweeps scheduler variant x offered load over the same serving stack
(serving/engine.py + serving/scheduler.py) and prints one JSON line per
variant. Each variant plays an identical seeded Poisson request stream —
a saturating low-priority "bulk" tenant plus sparse high-priority "vip"
probes — against the SAME page pool, and reports:

  - ttft_ms per priority class (p50/p99): the sweep's headline. Under
    saturation, fifo head-of-line-blocks the vip probes behind bulk
    work; priority admission jumps them to the front of the queue; spill
    preemption additionally evicts running bulk work, so vip p99 TTFT
    must drop variant over variant,
  - preemptions / restores / spilled_pages / host_bytes_peak: what the
    host tier moved to get there,
  - tenant_tokens + jain_fairness: tokens served per tenant and Jain's
    index over them (tools/fleet_report.py) — priority scheduling
    deliberately trades bulk fairness for vip latency; the index
    quantifies how much,
  - streams_identical: greedy token streams byte-identical across ALL
    variants at the same pool — scheduling may delay tokens, never
    change them.

Variants: {fifo, prio, spill} x {lo, hi} offered load.
  fifo  — scheduler_mode='fifo' (the bit-exact legacy baseline)
  prio  — scheduler_mode='priority' with preemption disabled: classes
          reorder the queue but running work is never evicted
  spill — full priority mode: preemption by KV page spill to host

Usage: python tools/sched_sweep.py [variant ...]
Variants: fifo-lo fifo-hi prio-lo prio-hi spill-lo spill-hi
          (default: all six)
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import bench  # noqa: E402
from tools.fleet_report import JainFairness  # noqa: E402

# (scheduler_mode, allow_preempt, load_scale) per variant; load_scale
# multiplies the offered arrival rate (hi ~ 4x past saturation)
VARIANTS = {
    "fifo-lo": ("fifo", False, 1.0),
    "fifo-hi": ("fifo", False, 4.0),
    "prio-lo": ("priority", False, 1.0),
    "prio-hi": ("priority", False, 4.0),
    "spill-lo": ("priority", True, 1.0),
    "spill-hi": ("priority", True, 4.0),
}


def _Build(jax):
  from lingvo_tpu.models.lm import layers as lm_layers
  on_cpu = jax.devices()[0].platform == "cpu"
  if on_cpu:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=128, model_dim=256, num_layers=2, num_heads=4,
        hidden_dim=512, use_rotary=True)
  else:
    p = lm_layers.TransformerLm.Params().Set(
        name="lm", vocab_size=32768, model_dim=1024, num_layers=8,
        num_heads=16, hidden_dim=4096, use_rotary=True)
  task = p.Instantiate()
  task.FinalizePaths()
  return task


def _Stream(rng, vocab, n_bulk, n_vip, bulk_out, vip_out, p_lo, p_hi,
            mean_gap_s, load_scale):
  """Seeded two-tenant mix: n_bulk priority-0 'bulk' requests saturate
  the pool; n_vip priority-5 'vip' probes arrive interleaved. Returns
  [(arrival_s, prompt, max_new, priority, tenant)] sorted by arrival."""
  reqs = []
  t = 0.0
  for _ in range(n_bulk):
    prompt = rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
        np.int32)
    reqs.append((t, prompt, bulk_out, 0, "bulk"))
    t += rng.exponential(mean_gap_s / load_scale)
  # vip probes spread across the bulk window
  span = max(t, 1e-6)
  for i in range(n_vip):
    prompt = rng.randint(1, vocab, rng.randint(p_lo, p_hi + 1)).astype(
        np.int32)
    reqs.append((span * (i + 1) / (n_vip + 1), prompt, vip_out, 5, "vip"))
  reqs.sort(key=lambda r: r[0])
  return reqs


def _Measure(jax, scheduler_mode, allow_preempt, load_scale):
  from lingvo_tpu.serving import engine as engine_lib
  on_tpu = jax.devices()[0].platform != "cpu"
  if on_tpu:
    n_bulk, n_vip, b_slots, page, max_seq = 24, 6, 8, 128, 1024
    bulk_out, vip_out, p_lo, p_hi = 192, 16, 32, 128
    mean_gap_s = 0.02
  else:
    n_bulk, n_vip, b_slots, page, max_seq = 10, 3, 2, 8, 64
    bulk_out, vip_out, p_lo, p_hi = 24, 4, 4, 12
    mean_gap_s = 0.01

  task = _Build(jax)
  theta = task.InstantiateVariables(jax.random.PRNGKey(0))
  rng = np.random.RandomState(0)
  reqs = _Stream(rng, task.p.vocab_size, n_bulk, n_vip, bulk_out, vip_out,
                 p_lo, p_hi, mean_gap_s, load_scale)

  # pool sized to b_slots x worst-case footprint: slots, not pages, are
  # the contended resource — preemption frees a SLOT by spilling pages
  full_pages = -(-(p_hi + bulk_out) // page)
  num_pages = b_slots * full_pages

  eng = engine_lib.ServingLoop(
      task, theta, page_size=page, num_pages=num_pages, max_batch=b_slots,
      max_seq_len=max_seq, prefill_chunk=16 if on_tpu else 4,
      scheduler_mode=scheduler_mode)
  eng.sched.allow_preempt = allow_preempt
  # compile the step program off the clock
  eng.RunBatch(np.array([[1, 2, 3, 4]], np.int32), np.array([4], np.int32), 2)
  eng.Start()
  t0 = time.perf_counter()
  handles = []
  for arrival, prompt, max_new, priority, tenant in reqs:
    dt = t0 + arrival - time.perf_counter()
    if dt > 0:
      time.sleep(dt)
    handles.append((eng.Submit(prompt, int(max_new), eos_id=None,
                               priority=priority, tenant=tenant),
                    priority, tenant))
  streams = [(h.Result(timeout=1200), pr, tn) for h, pr, tn in handles]
  wall = time.perf_counter() - t0
  stats = eng.Stats()
  host_peak = (eng.sched.host_store.Stats()["peak_host_bytes"]
               if eng.sched.host_store is not None else 0)
  eng.Stop()

  ttft_by_class: dict = {}
  for (h, pr, _tn) in handles:
    if h.first_token_time is not None:
      ttft_by_class.setdefault(pr, []).append(
          (h.first_token_time - h.submit_time) * 1e3)
  tenant_tokens: dict = {}
  for toks, _pr, tn in streams:
    tenant_tokens[tn] = tenant_tokens.get(tn, 0) + len(toks)

  sched = stats["scheduler"]
  return {
      "scheduler_mode": scheduler_mode,
      "allow_preempt": allow_preempt,
      "load_scale": load_scale,
      "requests": len(reqs),
      "slots": b_slots,
      "num_pages": num_pages,
      "wall_s": round(wall, 3),
      "ttft_ms": {
          f"c{pr}": {"p50": round(float(np.percentile(v, 50)), 2),
                     "p99": round(float(np.percentile(v, 99)), 2)}
          for pr, v in sorted(ttft_by_class.items())},
      "preemptions": sched["preemptions"],
      "restores": sched["restores"],
      "spilled_pages": sched["spilled_pages"],
      "restored_pages": sched["restored_pages"],
      "host_bytes_peak": host_peak,
      "tenant_tokens": tenant_tokens,
      "jain_fairness": round(JainFairness(tenant_tokens.values()), 4),
      "streams": [[int(t) for t in toks] for toks, _pr, _tn in streams],
  }


def main():
  bench._EnsureBackend()
  import gc
  import jax
  names = sys.argv[1:] or list(VARIANTS)
  baseline_streams: dict = {}   # load_scale -> first variant's streams
  for name in names:
    try:
      mode, preempt, load = VARIANTS[name]
      res = _Measure(jax, mode, preempt, load)
      # byte-identity across variants at the same offered load: compare
      # against the first variant measured at this load_scale
      streams = res.pop("streams")
      base = baseline_streams.setdefault(load, streams)
      res["streams_identical"] = streams == base
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({"variant": name, **res}), flush=True)
    gc.collect()


if __name__ == "__main__":
  main()
