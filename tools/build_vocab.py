#!/usr/bin/env python
"""Builds a vocab file from text shards (ref `lingvo/tools/wpm_encode_file.py`
/ vocab generation tools): counts whitespace tokens, writes the top-k with
special tokens first. Works for VocabFileTokenizer; for WPM/BPE train the
pieces with your favorite trainer and feed the files to
core.tokenizers.{Wpm,Bpe}Tokenizer."""

from __future__ import annotations

import argparse
import collections
import glob
import sys


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--input_glob", required=True)
  ap.add_argument("--output", required=True)
  ap.add_argument("--vocab_size", type=int, default=32000)
  ap.add_argument("--specials", default="<pad>,<s>,</s>,<unk>")
  args = ap.parse_args(argv)

  counts: collections.Counter = collections.Counter()
  files = sorted(glob.glob(args.input_glob))
  if not files:
    print(f"no files match {args.input_glob}", file=sys.stderr)
    return 1
  for path in files:
    with open(path, errors="replace") as f:
      for line in f:
        counts.update(line.split())
  specials = args.specials.split(",")
  budget = args.vocab_size - len(specials)
  vocab = specials + [w for w, _ in counts.most_common(budget)]
  with open(args.output, "w") as f:
    f.write("\n".join(vocab) + "\n")
  print(f"wrote {len(vocab)} tokens from {len(files)} files -> "
        f"{args.output}")
  return 0


if __name__ == "__main__":
  sys.exit(main())
