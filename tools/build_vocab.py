#!/usr/bin/env python
"""Builds a vocab from text shards (ref `lingvo/tools/wpm_encode_file.py`
/ vocab generation tools).

--format=words (default): counts whitespace tokens, writes the top-k with
special tokens first; works for VocabFileTokenizer.
--format=spm: trains a frequency-scored unigram SentencePiece `.model`
(core.sentencepiece.TrainUnigramModel) usable with
core.tokenizers.SentencePieceTokenizer.
For WPM/BPE piece files use your favorite trainer and feed the files to
core.tokenizers.{Wpm,Bpe}Tokenizer."""

from __future__ import annotations

import argparse
import collections
import glob
import sys


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--input_glob", required=True)
  ap.add_argument("--output", required=True)
  ap.add_argument("--vocab_size", type=int, default=32000)
  ap.add_argument("--specials", default="<pad>,<s>,</s>,<unk>")
  ap.add_argument("--format", choices=("words", "spm"), default="words")
  ap.add_argument("--byte_fallback", action="store_true",
                  help="spm only: add <0xXX> byte pieces for OOV coverage")
  args = ap.parse_args(argv)

  counts: collections.Counter = collections.Counter()
  files = sorted(glob.glob(args.input_glob))
  if not files:
    print(f"no files match {args.input_glob}", file=sys.stderr)
    return 1
  if args.format == "spm":
    import os
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__)), ".."))
    from lingvo_tpu.core import sentencepiece as spm

    def _Lines():  # stream: never materialize the corpus in memory
      for path in files:
        with open(path, errors="replace") as f:
          for line in f:
            line = line.strip()
            if line:
              yield line

    model = spm.TrainUnigramModel(_Lines(), args.vocab_size,
                                  byte_fallback=args.byte_fallback,
                                  specials=tuple(args.specials.split(",")))
    model.Save(args.output)
    print(f"wrote spm model ({model.vocab_size} pieces) from {len(files)} "
          f"files -> {args.output}")
    return 0
  for path in files:
    with open(path, errors="replace") as f:
      for line in f:
        counts.update(line.split())
  specials = args.specials.split(",")
  budget = args.vocab_size - len(specials)
  vocab = specials + [w for w, _ in counts.most_common(budget)]
  with open(args.output, "w") as f:
    f.write("\n".join(vocab) + "\n")
  print(f"wrote {len(vocab)} tokens from {len(files)} files -> "
        f"{args.output}")
  return 0


if __name__ == "__main__":
  sys.exit(main())
