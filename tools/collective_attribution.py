#!/usr/bin/env python
"""Attributes the collectives in a scale-config lowering to program sites.

VERDICT r4 Next #1(a): the MoELm64E lowering's collective histogram
(recorded as 204 all-to-alls / 181 collective-permutes / 218 all-reduces in
MULTICHIP_r04.json) was counted by a raw regex over the HLO *text*, which
matches the defining line twice (`%all-to-all.5 = ... all-to-all(...)`) and
every operand use once — so those numbers conflate instruction counts with
reference counts. This tool parses the optimized HLO properly:

  * counts only DEFINING instructions (one per collective op),
  * groups them per enclosing HLO computation (entry vs while-body — a
    collective inside the scan-over-layers body executes num_layers times
    per step but appears once),
  * attributes each to a program site via its `metadata={op_name=...}`
    scope string (gating / dispatch / combine / expert-ffn / attention /
    optimizer / backward etc.),
  * reports an EXECUTED count: textual count weighted by the scan trip
    count, the number that actually rides the ICI each step.

Usage:
  python tools/collective_attribution.py MoELm64E          # lower + analyze
  python tools/collective_attribution.py --hlo=dump.txt    # analyze a dump
Prints a human-readable table plus one JSON summary line (consumed by
__graft_entry__.dryrun_multichip for the round's MULTICHIP report).
"""

from __future__ import annotations

import collections
import json
import os
import re
import subprocess
import sys

COLLECTIVES = ("all-to-all", "all-gather", "all-reduce", "reduce-scatter",
               "collective-permute")

# op_name scope fragment -> site bucket, first match wins (most specific
# first). The scopes come from jax name_stack: layer paths like
# `stack/body/moe_layer/moe/...` plus transform prefixes like
# `transpose(jvp(...))` for the backward pass and `rematted_computation`
# for the remat replay.
_SITE_PATTERNS = (
    ("gating", r"top2gating|gating|sinkhorn|top_k"),
    ("moe-dispatch", r"dispatch|all_to_all"),
    ("moe-combine", r"combine"),
    ("moe-ffn", r"/moe/|expert"),
    ("attention", r"atten|flash"),
    ("softmax/emb", r"emb|softmax|logits"),
    ("optimizer", r"adafactor|optimizer|learner|clip|update"),
    ("loss/metrics", r"loss|metric|mean|xent"),
)


def _ParseHlo(hlo: str):
  """Yields (computation, opcode, op_name_metadata, line) per defining
  collective instruction."""
  comp = "?"
  # instruction definition: `  %name = type opcode(...)` — the opcode is the
  # token right after the result type; collective opcodes may carry a
  # `-start`/`-done` suffix (async pairs), which we fold into the base name
  # counting only the -start (the -done is the same transfer completing).
  # the opcode token follows the result type, which always ends with `]`
  # (array), `}` (layout), or `)` (tuple — may contain `/*index=N*/`
  # comments, so never scan with [^=]); operand USES are `%`-prefixed and
  # can't match this.
  inst_re = re.compile(
      r"[}\])]\s+(" + "|".join(COLLECTIVES) + r")(-start|-done)?\(")
  def_re = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=")
  comp_re = re.compile(r"^\s*(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s*->")
  meta_re = re.compile(r'op_name="([^"]*)"')
  for line in hlo.splitlines():
    m = comp_re.match(line)
    if m and "{" in line:
      comp = m.group(1)
      continue
    if not def_re.match(line):
      continue
    m = inst_re.search(line)
    if not m:
      continue
    if m.group(2) == "-done":
      continue
    meta = meta_re.search(line)
    yield comp, m.group(1), meta.group(1) if meta else "", line, m.start(1)


_DTYPE_BYTES = {"f64": 8, "f32": 4, "s32": 4, "u32": 4, "bf16": 2,
                "f16": 2, "s16": 2, "u16": 2, "s8": 1, "u8": 1, "pred": 1,
                "s64": 8, "u64": 8}


def _ResultBytes(line: str, opcode_start: int) -> int:
  """Total bytes of the instruction's result (sums tuple elements).

  The result type is everything between `=` and the opcode token — for
  tuple results (async all-to-all) that region contains parens/commas, so
  the caller passes the opcode's match position."""
  total = 0
  lhs = line[:opcode_start]
  for m in re.finditer(r"\b(f64|f32|bf16|f16|s64|u64|s32|u32|s16|u16|s8|u8|"
                       r"pred)\[([\d,]*)\]", lhs):
    dims = [int(d) for d in m.group(2).split(",") if d]
    n = 1
    for d in dims:
      n *= d
    total += n * _DTYPE_BYTES[m.group(1)]
  return total


def _Site(op_name: str) -> str:
  low = op_name.lower()
  phase = "bwd" if ("transpose" in low or "vjp" in low) else "fwd"
  if "rematted" in low or "remat" in low or "checkpoint" in low:
    phase = "remat"
  for site, pat in _SITE_PATTERNS:
    if re.search(pat, low):
      return f"{site}[{phase}]"
  if not op_name:
    return "(no-metadata)"
  # keep the last two scope components as the site name
  parts = [p for p in op_name.split("/") if p and not p.startswith("jit(")]
  return "/".join(parts[-2:]) + f"[{phase}]"


def _TripCounts(hlo: str) -> dict:
  """while-body computation name -> trip count (from XLA's induction-variable
  range analysis comments, `/*trip_count=N*/`, falling back to 1)."""
  trips = {}
  # while instructions: `%x = (...) while(...), condition=%cond, body=%body`
  # XLA's text dump annotates known trip counts on the backend config or in
  # the condition computation; simplest robust signal: constants compared in
  # the condition. We instead look for the canonical pattern
  # `body=%name ... /*trip_count=N*/` emitted by recent XLA versions.
  # XLA records known trip counts in the while op's backend_config JSON:
  # `body=%name, ... backend_config={..."known_trip_count":{"n":"12"}...}`
  for m in re.finditer(
      r'body=%?([\w.\-]+)[^\n]*?trip_count"\s*:\s*\{"n"\s*:\s*"(\d+)"', hlo):
    trips[m.group(1)] = int(m.group(2))
  return trips


def Analyze(hlo: str) -> dict:
  trips = _TripCounts(hlo)
  per_site = collections.Counter()
  per_site_exec = collections.Counter()
  per_site_bytes = collections.Counter()
  per_op = collections.Counter()
  per_op_exec = collections.Counter()
  per_op_bytes = collections.Counter()
  comps_seen = collections.Counter()
  for comp, op, op_name, line, op_start in _ParseHlo(hlo):
    # a computation reached through a while body executes trip_count times;
    # nested scans would need a call graph — single-level is what we emit
    trip = trips.get(comp, 1)
    site = _Site(op_name)
    nbytes = _ResultBytes(line, op_start) * trip
    per_site[(op, site)] += 1
    per_site_exec[(op, site)] += trip
    per_site_bytes[(op, site)] += nbytes
    per_op[op] += 1
    per_op_exec[op] += trip
    per_op_bytes[op] += nbytes
    comps_seen[comp] += 1
  return {
      "instructions": dict(per_op),
      "executed_per_step": dict(per_op_exec),
      "bytes_per_step": dict(per_op_bytes),
      "sites": {f"{op}|{site}": n for (op, site), n in per_site.items()},
      "sites_executed": {
          f"{op}|{site}": n for (op, site), n in per_site_exec.items()},
      "sites_bytes": {
          f"{op}|{site}": n for (op, site), n in per_site_bytes.items()},
      "trip_counts": trips,
      "computations_with_collectives": dict(comps_seen),
  }


def Report(analysis: dict) -> str:
  lines = []
  lines.append(f"{'collective':20s} {'defs':>6s} {'executed/step':>14s} "
               f"{'MB/step':>9s}")
  for op in COLLECTIVES:
    n = analysis["instructions"].get(op, 0)
    e = analysis["executed_per_step"].get(op, 0)
    mb = analysis["bytes_per_step"].get(op, 0) / 1e6
    if n:
      lines.append(f"{op:20s} {n:6d} {e:14d} {mb:9.1f}")
  lines.append("")
  lines.append("per-site (defs, executed, MB/step):")
  rows = sorted(analysis["sites"].items(),
                key=lambda kv: -analysis["sites_bytes"][kv[0]])
  for key, n in rows:
    e = analysis["sites_executed"][key]
    mb = analysis["sites_bytes"][key] / 1e6
    op, site = key.split("|", 1)
    lines.append(f"  {op:20s} {site:40s} {n:5d} {e:6d} {mb:9.1f}")
  if analysis["trip_counts"]:
    lines.append("")
    lines.append(f"scan trip counts: {analysis['trip_counts']}")
  return "\n".join(lines)


def main():
  args = sys.argv[1:]
  if args and args[0].startswith("--hlo="):
    hlo = open(args[0].split("=", 1)[1]).read()
  else:
    config = args[0] if args else "MoELm64E"
    dump = os.environ.get("SCALE_HLO_DUMP", f"/tmp/{config}_hlo.txt")
    env = dict(os.environ, SCALE_HLO_DUMP=dump)
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "scale_lowering.py")
    proc = subprocess.run([sys.executable, tool, config], env=env,
                          capture_output=True, text=True, timeout=2400)
    if proc.returncode != 0:
      print(proc.stderr[-2000:], file=sys.stderr)
      sys.exit(1)
    print(proc.stdout.strip().splitlines()[-1])  # the lowering report line
    hlo = open(dump).read()
  analysis = Analyze(hlo)
  print(Report(analysis))
  print(json.dumps({"collective_attribution": {
      "instructions": analysis["instructions"],
      "executed_per_step": analysis["executed_per_step"],
      "mb_per_step": {k: round(v / 1e6, 1)
                      for k, v in analysis["bytes_per_step"].items()},
  }}))


if __name__ == "__main__":
  main()
