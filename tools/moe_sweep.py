#!/usr/bin/env python
"""MoE performance sweep: where does the 64-expert step time go?

Runs controlled variants of the MoE bench config on the attached accelerator
and prints one JSON line per variant. The key control is the DENSE TWIN —
same dims/layers as the MoE's active path but with plain FFNs — which
separates "small-geometry MFU ceiling" from "MoE machinery overhead".

Usage: python tools/moe_sweep.py [variant ...]
Variants: dense_twin moe_b8 moe_b16 moe_b32 sinkhorn hash groups16 cap125
          einsum noflash experts8 experts16 experts32 experts64

The experts* ladder confirms the MoE scaling contract: total params grow
~linearly with the expert count while ACTIVE params/token (dense + top_k/E
of the expert weights) stay near-flat — so step time should too. On a CPU
host the geometry shrinks automatically so the ladder still runs.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402

import bench  # noqa: E402


def _Build(jax, jnp, model_registry, **kw):
  mp = model_registry.GetParams("lm.synthetic_packed_input.MoELmTiny",
                                "Train")
  mp.task.input = mp.input
  on_cpu = jax.devices()[0].platform == "cpu"
  if on_cpu:
    # CPU host: shrink to a geometry that steps in seconds so the expert
    # ladder / variant comparisons remain runnable without a TPU window
    mp.task.model_dim = 64
    mp.task.hidden_dim = 128
    mp.task.moe_hidden_dim = 128
    mp.task.num_heads = 4
    mp.task.num_layers = 2
    mp.task.num_experts = 64
    mp.task.moe_num_groups = 8
    mp.task.vocab_size = 512
    mp.task.input.vocab_size = 512
    mp.task.input.seq_len = 64
    mp.task.input.batch_size = 4
  else:
    mp.task.model_dim = 1024
    mp.task.hidden_dim = 4096
    mp.task.moe_hidden_dim = 2048
    mp.task.num_heads = 16
    mp.task.num_layers = 6
    mp.task.num_experts = 64
    mp.task.moe_num_groups = 8
    mp.task.vocab_size = 32768
    mp.task.input.vocab_size = 32768
    mp.task.input.seq_len = 1024
    mp.task.input.batch_size = 8
  mp.task.remat_policy = "dots"
  from lingvo_tpu.core import attention as attention_lib
  mp.task.atten_tpl = attention_lib.MultiHeadedAttention.Params().Set(
      use_flash_attention=not on_cpu)
  mp.task.fprop_dtype = jnp.bfloat16
  for k, v in kw.items():
    if k == "batch_size":
      mp.task.input.batch_size = v
    elif k == "use_flash":
      mp.task.atten_tpl.use_flash_attention = v
    elif k == "beta1":
      mp.task.train.learner.optimizer.beta1 = v
    else:
      setattr(mp.task, k, v)
  return mp


def _Phases(jax, jnp, mp):
  """Times fwd-only, fwd+bwd, and the full train step for one config —
  separates model compute from gradient and optimizer/param-traffic cost."""
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  from lingvo_tpu.core import input_policy
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)

  def _LossFn(theta):
    from lingvo_tpu.core import py_utils
    with py_utils.AuxLossContext() as aux:
      metrics, _ = task.FProp(theta, batch)
    total = jnp.asarray(metrics.loss[0], jnp.float32)
    return total + sum(jnp.asarray(v, jnp.float32) for v in aux.values())

  fwd = jax.jit(_LossFn)

  def _ValAndGradNorm(th):
    v, g = jax.value_and_grad(_LossFn)(th)
    return v + 0.0, sum(jnp.sum(jnp.square(x.astype(jnp.float32)))
                        for x in jax.tree_util.tree_leaves(g))

  fwdbwd = jax.jit(_ValAndGradNorm)
  res = {}
  for name, fn, fetch in (
      ("fwd_ms", fwd, float),
      ("fwdbwd_ms", fwdbwd, lambda o: float(o[0]) + float(o[1]))):
    res[name] = round(bench._MarginalStepTime(
        lambda _o, fn=fn: fn(state.theta), fetch, 3, 13) * 1e3, 2)

  step_fn = jax.jit(task.TrainStep, donate_argnums=(0,))
  holder = [state]

  def _Dispatch(_):
    holder[0], out = step_fn(holder[0], batch)
    return out

  res["train_ms"] = round(bench._MarginalStepTime(
      _Dispatch, lambda out: float(out.metrics.loss[0]), 3, 13) * 1e3, 2)
  return res


def _Micro(jax, jnp):
  """Times the MoE FFN layer's components in isolation at bench shapes:
  gating math, dispatch gather, expert FFN, full layer — fwd only."""
  from lingvo_tpu.parallel import gshard
  g, s, d, e, hdim = 8, 1024, 1024, 64, 2048
  key = jax.random.PRNGKey(0)
  x = jax.random.normal(key, (g, s, d), jnp.bfloat16)
  wg = jax.random.normal(key, (d, e), jnp.bfloat16) * 0.02
  wi = jax.random.normal(key, (e, d, hdim), jnp.bfloat16) * 0.02
  wo = jax.random.normal(key, (e, hdim, d), jnp.bfloat16) * 0.02
  c = int(s / e * 2.0)

  def _gating(a, wg, wi, wo):
    del wi, wo
    logits = jnp.einsum("GSD,DE->GSE", a, wg)
    out = gshard.Top2Gating(logits, None, 2.0, build_tensors=False)
    return out.indices, out.positions, out.gates

  def _dispatch(a, wg, wi, wo):
    del wi, wo
    gating = gshard.Top2Gating(
        jnp.einsum("GSD,DE->GSE", a, wg), None, 2.0, build_tensors=False)
    return gshard.IndexedDispatch(a, gating, e)

  ein = jnp.zeros((e, g, c, d), jnp.bfloat16)

  def _ffn_body(expert_in, wi, wo):
    h = jnp.einsum("EGCD,EDH->EGCH", expert_in, wi)
    h = jax.nn.relu(h)
    return jnp.einsum("EGCH,EHD->EGCD", h, wo)

  def _ffn(a, wg, wi, wo):
    del wg
    return _ffn_body(a, wi, wo)

  def _full(a, wg, wi, wo):
    gating = gshard.Top2Gating(
        jnp.einsum("GSD,DE->GSE", a, wg), None, 2.0, build_tensors=False)
    expert_in = gshard.IndexedDispatch(a, gating, e)
    return gshard.IndexedCombine(_ffn_body(expert_in, wi, wo), gating)

  res = {}
  for name, fn, arg in (("gating", _gating, x), ("dispatch", _dispatch, x),
                        ("ffn", _ffn, ein), ("full_layer", _full, x)):
    # scalar output (fetch = one float); weights are explicit args because
    # closed-over arrays embed as HLO constants and blow the tunnel's
    # compile-request size limit
    def _scalar(a, wg_, wi_, wo_, fn=fn):
      leaves = jax.tree_util.tree_leaves(fn(a, wg_, wi_, wo_))
      return sum(jnp.sum(l[..., :1].astype(jnp.float32)) for l in leaves)
    jfn = jax.jit(_scalar)
    res[f"{name}_ms"] = round(bench._MarginalStepTime(
        lambda _o, jf=jfn, a=arg: jf(a, wg, wi, wo), float, 3, 23) * 1e3, 3)
  return res


def _Time(jax, jnp, mp, peak):
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  from lingvo_tpu.core import input_policy, py_utils
  gen = input_policy.Instantiate(mp.input)
  batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
  step_fn = jax.jit(task.TrainStep, donate_argnums=(0,))
  holder = [state]

  def _Dispatch(_):
    holder[0], out = step_fn(holder[0], batch)
    return out

  step = bench._MarginalStepTime(
      _Dispatch, lambda out: float(out.metrics.loss[0]), 3, 13)
  ntok = int(np.prod(batch.ids.shape))
  n_params = py_utils.CountParams(holder[0].theta)
  expert_params = sum(
      int(np.prod(np.shape(v))) for k, v in holder[0].theta.FlattenItems()
      if ".moe." in f".{k}." and k.rsplit(".", 1)[-1] in ("wi", "wo"))
  gating = getattr(mp.task, "moe_gating_policy", "top2")
  # active experts/token: 1 for top-1 routers; 2 for top2; expert_choice
  # averages capacity_factor experts per token by construction
  if gating in ("sinkhorn", "hash"):
    top_k = 1.0
  elif gating == "expert_choice":
    top_k = float(getattr(mp.task, "moe_capacity_factor", 2.0))
  else:
    top_k = 2.0
  active = (n_params - expert_params) + \
      expert_params * top_k / max(mp.task.num_experts, 1)
  if mp.task.num_experts == 0:
    active = n_params
  b, t = batch.ids.shape
  flops = 6.0 * active * ntok + 12.0 * b * t * t * mp.task.model_dim * \
      mp.task.num_layers
  return {"step_ms": round(step * 1e3, 2),
          "tok_s": round(ntok / step, 1),
          "params_m": round(n_params / 1e6, 1),
          "active_m": round(active / 1e6, 1),
          "mfu": round(flops / (step * peak), 4)}


VARIANTS = {
    "dense_twin": dict(num_experts=0, hidden_dim=4096),
    "moe_b8": dict(),
    "moe_b16": dict(batch_size=16),
    "moe_b32": dict(batch_size=32),
    "sinkhorn": dict(moe_gating_policy="sinkhorn"),
    "hash": dict(moe_gating_policy="hash"),
    "expert_choice": dict(moe_gating_policy="expert_choice"),
    "groups16": dict(moe_num_groups=16),
    "groups32": dict(moe_num_groups=32),
    "cap125": dict(moe_capacity_factor=1.25),
    "einsum": dict(moe_dispatch_method="einsum"),
    "noflash": dict(use_flash=False),
    "noremat": dict(remat_policy="none"),
    "b16_groups16": dict(batch_size=16, moe_num_groups=16),
    "dense_twin_b16": dict(num_experts=0, hidden_dim=4096, batch_size=16),
    "nomom_b8": dict(beta1=0.0),
    "nomom_b16": dict(beta1=0.0, batch_size=16),
    "nomom_b24": dict(beta1=0.0, batch_size=24),
    "moe_b24": dict(batch_size=24),
    # expert-count ladder: total params scale ~E, active params ~flat
    "experts8": dict(num_experts=8),
    "experts16": dict(num_experts=16),
    "experts32": dict(num_experts=32),
    "experts64": dict(),
}


# Priority order for the unattended post-bench sweep (bench.py runs this
# the moment a TPU probe succeeds — tunnel windows are short, so the most
# decision-relevant variants go first; each result lands on disk
# immediately).
AUTO_SWEEP = ("moe_b8", "dense_twin", "moe_b16", "groups16", "groups32",
              "cap125", "expert_choice", "hash", "einsum", "micro",
              "phases:moe_b8", "moe_b32", "sinkhorn", "noflash",
              "experts8", "experts16", "experts32")


def RunSweep(names=AUTO_SWEEP, budget_s: float = 1500.0,
             out_path: str | None = None, log=None):
  """Runs sweep variants under a wall-clock budget; appends one JSON line
  per variant to out_path (jsonl) and returns the result list. Assumes the
  jax backend is already initialized (call from bench.py post-bench)."""
  import gc
  import time as _time
  import jax
  import jax.numpy as jnp
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401
  log = log or (lambda msg: print(msg, file=sys.stderr))
  peak = bench._PeakFlops(jax.devices()[0])
  t0 = _time.time()
  results = []
  for name in names:
    if _time.time() - t0 > budget_s:
      log(f"moe_sweep: budget exhausted after {len(results)} variants")
      break
    try:
      if name == "micro":
        res = _Micro(jax, jnp)
      elif name.startswith("phases:"):
        res = _Phases(jax, jnp,
                      _Build(jax, jnp, model_registry,
                             **VARIANTS[name.split(":", 1)[1]]))
      else:
        res = _Time(jax, jnp, _Build(jax, jnp, model_registry,
                                     **VARIANTS[name]), peak)
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    row = {"variant": name, **res}
    results.append(row)
    log(f"moe_sweep: {json.dumps(row)}")
    if out_path:
      with open(out_path, "a") as f:
        f.write(json.dumps(row) + "\n")
    gc.collect()
  return results


def WriteBaselineSection(results, baseline_path: str) -> None:
  """Rewrites the auto-sweep block in BASELINE.md (between the MOE_SWEEP
  markers; appends the block if absent) with the latest TPU sweep."""
  import time as _time
  begin = "<!-- MOE_SWEEP_AUTO_BEGIN -->"
  end = "<!-- MOE_SWEEP_AUTO_END -->"
  lines = [begin,
           f"### MoE sweep (auto-run on TPU probe success, "
           f"{_time.strftime('%Y-%m-%d %H:%M UTC', _time.gmtime())})", "",
           "| Variant | step ms | tok/s | MFU |", "|---|---|---|---|"]
  for r in results:
    if "error" in r:
      lines.append(f"| {r['variant']} | error: {r['error'][:60]} | | |")
    elif "mfu" in r:
      lines.append(f"| {r['variant']} | {r.get('step_ms', '')} | "
                   f"{r.get('tok_s', '')} | {r['mfu']} |")
    else:  # micro / phases rows
      detail = {k: v for k, v in r.items() if k != "variant"}
      lines.append(f"| {r['variant']} | {json.dumps(detail)[:90]} | | |")
  lines.append(end)
  block = "\n".join(lines)
  try:
    text = open(baseline_path).read()
  except FileNotFoundError:
    text = ""
  if begin in text and end in text:
    pre = text.split(begin)[0]
    post = text.split(end, 1)[1]
    text = pre + block + post
  else:
    text = text.rstrip() + "\n\n" + block + "\n"
  with open(baseline_path, "w") as f:
    f.write(text)


def main():
  bench._EnsureBackend()
  import gc
  import jax
  import jax.numpy as jnp
  try:
    cache_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "..", ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
  except Exception:  # noqa: BLE001
    pass
  from lingvo_tpu import model_registry
  import lingvo_tpu.models.all_params  # noqa: F401
  peak = bench._PeakFlops(jax.devices()[0])
  names = sys.argv[1:] or ["dense_twin", "moe_b8", "moe_b16"]
  for name in names:
    try:
      if name == "micro":
        res = _Micro(jax, jnp)
      elif name.startswith("phases:"):
        res = _Phases(jax, jnp,
                      _Build(jax, jnp, model_registry,
                             **VARIANTS[name.split(":", 1)[1]]))
      else:
        res = _Time(jax, jnp, _Build(jax, jnp, model_registry,
                                     **VARIANTS[name]), peak)
    except Exception as e:  # noqa: BLE001
      res = {"error": f"{type(e).__name__}: {e}"[:200]}
    print(json.dumps({"variant": name, **res}), flush=True)
    gc.collect()


if __name__ == "__main__":
  main()
