#!/usr/bin/env python
"""Emits Kubernetes YAML for a TPU training job + follower evaler/decoder +
tensorboard (ref `lingvo/tools/gke_launch.py` up/down/reload verbs; this
writes the manifests — apply them with kubectl)."""

from __future__ import annotations

import argparse
import sys

JOB_TEMPLATE = """\
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  backoffLimit: 2
  template:
    spec:
      restartPolicy: Never
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {accelerator}
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
      - name: {name}
        image: {image}
        command: ["python", "-m", "lingvo_tpu.trainer"]
        args: ["--model={model}", "--logdir={logdir}", "--mode={mode}",
               "--job={job}"]
        resources:
          limits:
            google.com/tpu: {num_chips}
"""

TB_TEMPLATE = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}-tensorboard
spec:
  replicas: 1
  selector:
    matchLabels: {{app: {name}-tensorboard}}
  template:
    metadata:
      labels: {{app: {name}-tensorboard}}
    spec:
      containers:
      - name: tensorboard
        image: {image}
        command: ["tensorboard", "--logdir={logdir}", "--host=0.0.0.0"]
        ports:
        - containerPort: 6006
"""


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--name", required=True)
  ap.add_argument("--model", required=True)
  ap.add_argument("--image", required=True)
  ap.add_argument("--logdir", required=True, help="GCS path.")
  ap.add_argument("--accelerator", default="tpu-v5p-slice")
  ap.add_argument("--topology", default="2x2x1")
  ap.add_argument("--num_chips", type=int, default=4)
  ap.add_argument("--with_evaler", action="store_true")
  ap.add_argument("--output", default="-")
  args = ap.parse_args(argv)

  docs = [JOB_TEMPLATE.format(
      name=f"{args.name}-train", model=args.model, image=args.image,
      logdir=args.logdir, mode="train", job="executor_tpu",
      accelerator=args.accelerator, topology=args.topology,
      num_chips=args.num_chips)]
  if args.with_evaler:
    docs.append(JOB_TEMPLATE.format(
        name=f"{args.name}-evaler", model=args.model, image=args.image,
        logdir=args.logdir, mode="eval", job="evaler",
        accelerator=args.accelerator, topology=args.topology, num_chips=1))
  docs.append(TB_TEMPLATE.format(name=args.name, image=args.image,
                                 logdir=args.logdir))
  yaml = "---\n".join(docs)
  if args.output == "-":
    print(yaml)
  else:
    with open(args.output, "w") as f:
      f.write(yaml)
  return 0


if __name__ == "__main__":
  sys.exit(main())
