#!/usr/bin/env python
"""GKE launcher: build/up/down/reload verbs around a TPU training job +
follower evaler + tensorboard (ref `lingvo/tools/gke_launch.py:398` verb
dispatch; `print` emits the manifests, `build` docker-builds + pushes the
image, `up` applies, `down` deletes, `reload` = down + up).

Examples:
  gke_launch.py print --name=lm1 --model=lm.synthetic_packed_input.DenseLm8B \
      --image=gcr.io/proj/lingvo-tpu:live --logdir=gs://bucket/lm1
  gke_launch.py build --image=gcr.io/proj/lingvo-tpu:live
  gke_launch.py up --name=lm1 ... [--build]
  gke_launch.py down --name=lm1
  gke_launch.py reload --name=lm1 ...
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile

JOB_TEMPLATE = """\
apiVersion: batch/v1
kind: Job
metadata:
  name: {name}
spec:
  backoffLimit: 2
  template:
    spec:
      restartPolicy: Never
      nodeSelector:
        cloud.google.com/gke-tpu-accelerator: {accelerator}
        cloud.google.com/gke-tpu-topology: {topology}
      containers:
      - name: {name}
        image: {image}
        command: ["python", "-m", "lingvo_tpu.trainer"]
        args: ["--model={model}", "--logdir={logdir}", "--mode={mode}",
               "--job={job}"]
        resources:
          limits:
            google.com/tpu: {num_chips}
"""

TB_TEMPLATE = """\
apiVersion: apps/v1
kind: Deployment
metadata:
  name: {name}-tensorboard
spec:
  replicas: 1
  selector:
    matchLabels: {{app: {name}-tensorboard}}
  template:
    metadata:
      labels: {{app: {name}-tensorboard}}
    spec:
      containers:
      - name: tensorboard
        image: {image}
        command: ["tensorboard", "--logdir={logdir}", "--host=0.0.0.0"]
        ports:
        - containerPort: 6006
"""


def BuildManifests(args) -> str:
  docs = [JOB_TEMPLATE.format(
      name=f"{args.name}-train", model=args.model, image=args.image,
      logdir=args.logdir, mode="train", job="executor_tpu",
      accelerator=args.accelerator, topology=args.topology,
      num_chips=args.num_chips)]
  if args.with_evaler:
    docs.append(JOB_TEMPLATE.format(
        name=f"{args.name}-evaler", model=args.model, image=args.image,
        logdir=args.logdir, mode="eval", job="evaler",
        accelerator=args.accelerator, topology=args.topology, num_chips=1))
  docs.append(TB_TEMPLATE.format(name=args.name, image=args.image,
                                 logdir=args.logdir))
  return "---\n".join(docs)


def _Run(cmd: list[str], dry_run: bool) -> int:
  print("+ " + " ".join(cmd), file=sys.stderr)
  if dry_run:
    return 0
  return subprocess.call(cmd)


def DoPrint(args) -> int:
  yaml = BuildManifests(args)
  if args.output == "-":
    print(yaml)
  else:
    with open(args.output, "w") as f:
      f.write(yaml)
  return 0


def DoBuild(args) -> int:
  """docker build + push (ref gke_launch build_docker_image)."""
  root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
  rc = _Run(["docker", "build", "-t", args.image, "-f", args.dockerfile,
             root], args.dry_run)
  if rc:
    return rc
  return _Run(["docker", "push", args.image], args.dry_run)


def DoUp(args) -> int:
  if args.build:
    rc = DoBuild(args)
    if rc:
      return rc
  with tempfile.NamedTemporaryFile(
      "w", suffix=".yaml", delete=False) as f:
    f.write(BuildManifests(args))
    path = f.name
  try:
    return _Run(["kubectl", "apply", "-f", path], args.dry_run)
  finally:
    # dry-run keeps the manifest so the printed command is replayable
    if not args.keep_manifest and not args.dry_run:
      os.unlink(path)


def DoDown(args) -> int:
  rc = 0
  for resource in (f"job/{args.name}-train", f"job/{args.name}-evaler",
                   f"deployment/{args.name}-tensorboard"):
    rc |= _Run(["kubectl", "delete", "--ignore-not-found", resource],
               args.dry_run)
  return rc


def DoReload(args) -> int:
  rc = DoDown(args)
  if rc:
    return rc
  return DoUp(args)


def _AddCommonFlags(ap, need_model: bool):
  ap.add_argument("--name", required=True)
  ap.add_argument("--image", required=need_model)
  if need_model:
    ap.add_argument("--model", required=True)
    ap.add_argument("--logdir", required=True, help="GCS path.")
    ap.add_argument("--accelerator", default="tpu-v5p-slice")
    ap.add_argument("--topology", default="2x2x1")
    ap.add_argument("--num_chips", type=int, default=4)
    ap.add_argument("--with_evaler", action="store_true")
  ap.add_argument("--dry_run", action="store_true",
                  help="Print the docker/kubectl commands, don't run them.")


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  sub = ap.add_subparsers(dest="verb", required=True)

  p_print = sub.add_parser("print", help="Emit manifests.")
  _AddCommonFlags(p_print, need_model=True)
  p_print.add_argument("--output", default="-")
  p_print.set_defaults(fn=DoPrint)

  p_build = sub.add_parser("build", help="docker build + push the image.")
  p_build.add_argument("--image", required=True)
  p_build.add_argument("--dockerfile", default="docker/dev.dockerfile")
  p_build.add_argument("--dry_run", action="store_true")
  p_build.set_defaults(fn=DoBuild)

  p_up = sub.add_parser("up", help="Apply manifests (optionally build).")
  _AddCommonFlags(p_up, need_model=True)
  p_up.add_argument("--build", action="store_true")
  p_up.add_argument("--dockerfile", default="docker/dev.dockerfile")
  p_up.add_argument("--keep_manifest", action="store_true")
  p_up.set_defaults(fn=DoUp)

  p_down = sub.add_parser("down", help="Delete the jobs + tensorboard.")
  _AddCommonFlags(p_down, need_model=False)
  p_down.set_defaults(fn=DoDown)

  p_reload = sub.add_parser("reload", help="down then up.")
  _AddCommonFlags(p_reload, need_model=True)
  p_reload.add_argument("--build", action="store_true")
  p_reload.add_argument("--dockerfile", default="docker/dev.dockerfile")
  p_reload.add_argument("--keep_manifest", action="store_true")
  p_reload.set_defaults(fn=DoReload)

  args = ap.parse_args(argv)
  return args.fn(args)


if __name__ == "__main__":
  sys.exit(main())
