#!/usr/bin/env python
"""Counts records in sharded files via the native yielder (ref
`lingvo/tools/count_records.py`)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--input", required=True,
                  help="'type:glob' pattern (text/tfrecord/recordio).")
  args = ap.parse_args(argv)
  from lingvo_tpu.ops import native
  y = native.RecordYielder(args.input, shuffle=False, max_epochs=1,
                           num_threads=1)
  n = sum(1 for _ in y)
  print(n)
  return 0


if __name__ == "__main__":
  sys.exit(main())
