#!/usr/bin/env python
"""Prints the first N records of sharded files (ref
`lingvo/tools/print_tf_records.py`)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--input", required=True)
  ap.add_argument("--limit", type=int, default=10)
  args = ap.parse_args(argv)
  from lingvo_tpu.ops import native
  y = native.RecordYielder(args.input, shuffle=False, max_epochs=1,
                           num_threads=1)
  for i, rec in enumerate(y):
    if i >= args.limit:
      break
    print(rec[:200])
  return 0


if __name__ == "__main__":
  sys.exit(main())
