#!/usr/bin/env python
"""Corpus statistics over record shards (ref `lingvo/tools/compute_stats.py`):
record counts, byte-length and (for text) whitespace-token-length
distributions — the numbers needed to pick input-generator bucket
boundaries.

Usage: compute_stats.py --input_glob='data/*.tfrecord' [--format=tfrecord]
       compute_stats.py --input_glob='data/*.txt' --format=text
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))


def _IterRecords(files, fmt):
  if fmt == "text":
    for path in files:
      with open(path, "rb") as f:
        for line in f:
          yield line.rstrip(b"\n")
  else:
    import struct
    for path in files:
      # tfrecord framing: u64 len, u32 len-crc, payload, u32 payload-crc
      with open(path, "rb") as f:
        while True:
          hdr = f.read(12)
          if len(hdr) < 12:
            break
          (ln,) = struct.unpack("<Q", hdr[:8])
          payload = f.read(ln)
          if len(payload) < ln:
            break
          f.read(4)
          yield payload


def _Describe(name, values):
  arr = np.asarray(values)
  if not len(arr):
    print(f"{name}: no data")
    return
  pcts = np.percentile(arr, [50, 90, 95, 99])
  print(f"{name}: n={len(arr)} mean={arr.mean():.1f} max={arr.max()} "
        f"p50={pcts[0]:.0f} p90={pcts[1]:.0f} p95={pcts[2]:.0f} "
        f"p99={pcts[3]:.0f}")


def main(argv=None) -> int:
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--input_glob", required=True)
  ap.add_argument("--format", choices=("tfrecord", "text"), default="text")
  ap.add_argument("--suggest_buckets", type=int, default=0,
                  help="If >0, print this many token-length bucket bounds.")
  args = ap.parse_args(argv)

  files = sorted(glob.glob(args.input_glob))
  if not files:
    print(f"no files match {args.input_glob}", file=sys.stderr)
    return 1
  byte_lens, tok_lens = [], []
  for rec in _IterRecords(files, args.format):
    byte_lens.append(len(rec))
    tok_lens.append(len(rec.split()))
  print(f"{len(files)} files")
  _Describe("bytes/record", byte_lens)
  _Describe("tokens/record", tok_lens)
  if args.suggest_buckets and tok_lens:
    qs = np.linspace(0, 100, args.suggest_buckets + 1)[1:]
    bounds = sorted({int(np.ceil(b))
                     for b in np.percentile(tok_lens, qs)})
    print(f"suggested bucket_upper_bound: {bounds}")
  return 0


if __name__ == "__main__":
  sys.exit(main())
