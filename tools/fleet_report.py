#!/usr/bin/env python3
"""Fleet report: scrape N /statusz endpoints and print one merged view.

Each serving engine / trainer process exposes /statusz when constructed
with `serve_port` (lingvo_tpu/observe/export.py). This tool polls any
number of them (observe/aggregate.py), merges the registry snapshots —
counters sum, histogram buckets merge, gauges stay per-replica — and
prints:

- a fleet totals table (summed counters, merged-histogram p50/p99);
- a per-replica gauge table (queue depth, active slots, config facts);
- the least-loaded replica (the router's admission choice);
- any unreachable replicas, each with its error.

Usage:
  python tools/fleet_report.py host1:8080 host2:8080 ...
  python tools/fleet_report.py --json host1:8080 host2:8080
"""

from __future__ import annotations

import json
import sys

from lingvo_tpu.observe import aggregate
from lingvo_tpu.observe import metrics as metrics_lib


def FleetReport(docs: dict) -> str:
  """The human-readable report over {label: statusz doc (or error)}."""
  lines = []
  errors = {k: v["error"] for k, v in docs.items() if "error" in v}
  live = {k: v for k, v in docs.items() if "snapshot" in v}
  merged = aggregate.MergeStatusz(live)
  lines.append(f"replicas: {len(live)} live, {len(errors)} unreachable")
  for label, err in sorted(errors.items()):
    lines.append(f"  DOWN {label}: {err}")
  lines.append("")
  lines.append("fleet totals (counters summed, histograms merged):")
  for name in sorted(merged["fleet"]):
    v = merged["fleet"][name]
    if isinstance(v, dict):   # merged histogram: show count + quantiles
      q = metrics_lib.HistogramQuantiles(v, qs=(0.5, 0.99))
      lines.append(f"  {name:<44} n={v['count']:<8} "
                   f"p50={q[0.5]:.4g} p99={q[0.99]:.4g}")
    else:
      lines.append(f"  {name:<44} {v}")
  lines.append("")
  lines.append("per-replica gauges:")
  for label in merged["replicas"]:
    lines.append(f"  [{label}]")
    gauges = merged["per_replica"].get(label, {})
    for name in sorted(gauges):
      v = gauges[name]
      if isinstance(v, (dict, list)):
        continue   # structured values belong to the raw /statusz
      lines.append(f"    {name:<42} {v}")
  target = aggregate.LeastLoaded(live)
  if target is not None:
    lines.append("")
    lines.append(f"least-loaded replica (scheduler/queue_depth): {target}")
  return "\n".join(lines)


def main(argv=None) -> int:
  argv = sys.argv[1:] if argv is None else argv
  as_json = "--json" in argv
  urls = [a for a in argv if not a.startswith("--")]
  if not urls:
    print(__doc__, file=sys.stderr)
    return 2
  docs = aggregate.ScrapeAll(urls)
  if as_json:
    out = {"merged": aggregate.MergeStatusz(docs),
           "least_loaded": aggregate.LeastLoaded(docs),
           "errors": {k: v["error"] for k, v in docs.items()
                      if "error" in v}}
    print(json.dumps(out, indent=1, default=str))
  else:
    print(FleetReport(docs))
  # partial fleet visibility is still a report, but exit nonzero when
  # NOTHING answered so cron/scripts notice a dead fleet
  return 0 if any("snapshot" in v for v in docs.values()) else 1


if __name__ == "__main__":
  sys.exit(main())
