#!/usr/bin/env python3
"""Fleet report: scrape N /statusz endpoints and print one merged view.

Each serving engine / trainer process exposes /statusz when constructed
with `serve_port` (lingvo_tpu/observe/export.py). This tool polls any
number of them (observe/aggregate.py), merges the registry snapshots —
counters sum, histogram buckets merge, gauges stay per-replica — and
prints:

- a fleet totals table (summed counters, merged-histogram p50/p99);
- a per-replica gauge table (queue depth, active slots, config facts);
- router fairness + per-replica utilization: each replica's share of
  the fleet's emitted/prefilled tokens and the Jain fairness index over
  both (1.0 = perfectly even; 1/N = one replica does all the work — a
  prefix-aware router intentionally trades some fairness for cache
  affinity, so read this column against `router/prefix_routed`);
- the least-loaded replica (the router's admission choice);
- any unreachable replicas, each with its error.

Usage:
  python tools/fleet_report.py host1:8080 host2:8080 ...
  python tools/fleet_report.py --json host1:8080 host2:8080
"""

from __future__ import annotations

import json
import sys

from lingvo_tpu.observe import aggregate
from lingvo_tpu.observe import metrics as metrics_lib


def JainFairness(values) -> float:
  """Jain's fairness index over per-replica work counts: (sum x)^2 /
  (n * sum x^2). 1.0 when perfectly even, 1/n when one replica does
  everything; an idle fleet (all zero) reads as fair."""
  xs = [float(v) for v in values]
  if not xs:
    return 1.0
  sq = sum(x * x for x in xs)
  if sq == 0.0:
    return 1.0
  return (sum(xs) ** 2) / (len(xs) * sq)


def Utilization(docs: dict) -> dict:
  """Per-replica utilization + fairness over {label: statusz doc}.

  Reads each live replica's `serving/tokens_emitted` (decode work) and
  `serving/prompt_tokens` (prefill work actually computed — prefix-cache
  hits don't count, which is exactly why a prefix router skews this
  column on purpose) plus `scheduler/queue_depth`, and computes the
  Jain index over both work distributions."""
  per = {}
  for label in sorted(docs):
    doc = docs[label]
    if not isinstance(doc, dict) or "snapshot" not in doc:
      continue
    snap = doc["snapshot"]

    def _Num(key):
      v = snap.get(key, 0)
      return v if isinstance(v, (int, float)) and not isinstance(v, bool) \
          else 0
    per[label] = {
        "tokens_emitted": _Num("serving/tokens_emitted"),
        "prompt_tokens": _Num("serving/prompt_tokens"),
        "queue_depth": _Num("scheduler/queue_depth"),
    }
  tot_e = sum(r["tokens_emitted"] for r in per.values())
  tot_p = sum(r["prompt_tokens"] for r in per.values())
  for r in per.values():
    r["decode_share"] = r["tokens_emitted"] / tot_e if tot_e else 0.0
    r["prefill_share"] = r["prompt_tokens"] / tot_p if tot_p else 0.0
  return {
      "per_replica": per,
      "decode_fairness": JainFairness(
          r["tokens_emitted"] for r in per.values()),
      "prefill_fairness": JainFairness(
          r["prompt_tokens"] for r in per.values()),
  }


def FleetReport(docs: dict) -> str:
  """The human-readable report over {label: statusz doc (or error)}."""
  lines = []
  errors = {k: v["error"] for k, v in docs.items() if "error" in v}
  live = {k: v for k, v in docs.items() if "snapshot" in v}
  merged = aggregate.MergeStatusz(live)
  lines.append(f"replicas: {len(live)} live, {len(errors)} unreachable")
  for label, err in sorted(errors.items()):
    lines.append(f"  DOWN {label}: {err}")
  lines.append("")
  lines.append("fleet totals (counters summed, histograms merged):")
  for name in sorted(merged["fleet"]):
    v = merged["fleet"][name]
    if isinstance(v, dict):   # merged histogram: show count + quantiles
      q = metrics_lib.HistogramQuantiles(v, qs=(0.5, 0.99))
      lines.append(f"  {name:<44} n={v['count']:<8} "
                   f"p50={q[0.5]:.4g} p99={q[0.99]:.4g}")
    else:
      lines.append(f"  {name:<44} {v}")
  lines.append("")
  lines.append("per-replica gauges:")
  for label in merged["replicas"]:
    lines.append(f"  [{label}]")
    gauges = merged["per_replica"].get(label, {})
    for name in sorted(gauges):
      v = gauges[name]
      if isinstance(v, (dict, list)):
        continue   # structured values belong to the raw /statusz
      lines.append(f"    {name:<42} {v}")
  util = Utilization(live)
  if util["per_replica"]:
    lines.append("")
    lines.append("router fairness / per-replica utilization:")
    lines.append(f"  {'replica':<20} {'decode_tok':>10} {'share':>7} "
                 f"{'prefill_tok':>11} {'share':>7} {'queue':>6}")
    for label, r in util["per_replica"].items():
      lines.append(
          f"  {label:<20} {r['tokens_emitted']:>10} "
          f"{r['decode_share']:>7.2%} {r['prompt_tokens']:>11} "
          f"{r['prefill_share']:>7.2%} {r['queue_depth']:>6}")
    lines.append(f"  jain fairness: decode={util['decode_fairness']:.3f} "
                 f"prefill={util['prefill_fairness']:.3f}")
  target = aggregate.LeastLoaded(live)
  if target is not None:
    lines.append("")
    lines.append(f"least-loaded replica (scheduler/queue_depth): {target}")
  return "\n".join(lines)


def main(argv=None) -> int:
  argv = sys.argv[1:] if argv is None else argv
  as_json = "--json" in argv
  urls = [a for a in argv if not a.startswith("--")]
  if not urls:
    print(__doc__, file=sys.stderr)
    return 2
  docs = aggregate.ScrapeAll(urls)
  if as_json:
    out = {"merged": aggregate.MergeStatusz(docs),
           "utilization": Utilization(docs),
           "least_loaded": aggregate.LeastLoaded(docs),
           "errors": {k: v["error"] for k, v in docs.items()
                      if "error" in v}}
    print(json.dumps(out, indent=1, default=str))
  else:
    print(FleetReport(docs))
  # partial fleet visibility is still a report, but exit nonzero when
  # NOTHING answered so cron/scripts notice a dead fleet
  return 0 if any("snapshot" in v for v in docs.values()) else 1


if __name__ == "__main__":
  sys.exit(main())
