#!/usr/bin/env python3
"""Per-request latency report from an exported serving trace.

Input: the Chrome trace-event JSON written by
`TraceRecorder.Export(path)` (lingvo_tpu/observe/trace.py). The file is
Perfetto-openable; this tool consumes the extra top-level `perRequest`
key (ignored by trace viewers) and prints:

- a per-request table: slot, prompt/output tokens, pages, queue wait,
  TTFT, per-output-token latency, total, finish reason;
- aggregate TTFT / TPOT / total-latency p50/p99;
- a queue-wait histogram (how long requests sat before admission).

With MULTIPLE trace files (one per serving replica) it prints a merged
per-replica latency table instead — one row per file plus a fleet row
computed over the union of requests.

Usage:
  python tools/trace_report.py /tmp/serving_trace.json
  python tools/trace_report.py /tmp/replica_a.json /tmp/replica_b.json
"""

from __future__ import annotations

import json
import sys

import numpy as np


def LoadTrace(path: str) -> dict:
  with open(path) as f:
    trace = json.load(f)
  if "perRequest" not in trace:
    raise ValueError(
        f"{path}: no perRequest key — not a TraceRecorder.Export file")
  return trace


def _Percentiles(values) -> dict:
  vals = [v for v in values if v is not None]
  if not vals:
    return {"n": 0}
  arr = np.asarray(vals, np.float64)
  return {
      "n": int(arr.size),
      "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 3),
      "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 3),
      "mean_ms": round(float(arr.mean()) * 1e3, 3),
      "max_ms": round(float(arr.max()) * 1e3, 3),
  }


def _QueueWaitHistogram(waits, n_buckets: int = 8) -> list:
  """[(upper_bound_ms, count)] over the observed queue-wait range."""
  vals = np.asarray([w for w in waits if w is not None], np.float64)
  if vals.size == 0:
    return []
  hi = max(float(vals.max()), 1e-6)
  bounds = np.linspace(hi / n_buckets, hi, n_buckets)
  out = []
  prev = 0.0
  for b in bounds:
    n = int(np.sum((vals > prev) & (vals <= b))) + (
        int(np.sum(vals == 0.0)) if prev == 0.0 else 0)
    out.append((round(b * 1e3, 3), n))
    prev = b
  return out


def Summary(trace: dict) -> dict:
  """Aggregate metrics from a loaded trace dict."""
  reqs = list(trace["perRequest"].values())
  return {
      "requests": len(reqs),
      "complete": sum(1 for r in reqs if r.get("total_s") is not None),
      "tokens": sum(r.get("tokens", 0) for r in reqs),
      "ttft": _Percentiles([r.get("ttft_s") for r in reqs]),
      "tpot": _Percentiles([r.get("tpot_s") for r in reqs]),
      "total": _Percentiles([r.get("total_s") for r in reqs]),
      "queue_wait": _Percentiles([r.get("queue_wait_s") for r in reqs]),
      "queue_wait_hist_ms": _QueueWaitHistogram(
          [r.get("queue_wait_s") for r in reqs]),
  }


def _Ms(v) -> str:
  return "-" if v is None else f"{v * 1e3:.2f}"


def Report(trace: dict) -> str:
  """The human-readable report (table + percentiles + histogram)."""
  reqs = sorted(trace["perRequest"].items(), key=lambda kv: int(kv[0]))
  header = (f"{'req':>5} {'slot':>4} {'prompt':>6} {'tokens':>6} "
            f"{'pages':>5} {'queue_ms':>9} {'ttft_ms':>9} {'tpot_ms':>9} "
            f"{'total_ms':>9}  reason")
  lines = [header, "-" * len(header)]
  for rid, r in reqs:
    lines.append(
        f"{rid:>5} {str(r.get('slot', '-')):>4} "
        f"{r.get('prompt_tokens', 0):>6} {r.get('tokens', 0):>6} "
        f"{r.get('pages', 0):>5} {_Ms(r.get('queue_wait_s')):>9} "
        f"{_Ms(r.get('ttft_s')):>9} {_Ms(r.get('tpot_s')):>9} "
        f"{_Ms(r.get('total_s')):>9}  {r.get('finish_reason') or 'open'}")
  s = Summary(trace)
  lines.append("")
  for name in ("ttft", "tpot", "total", "queue_wait"):
    p = s[name]
    if p.get("n"):
      lines.append(f"{name:>10}: p50 {p['p50_ms']} ms   p99 {p['p99_ms']} "
                   f"ms   mean {p['mean_ms']} ms   (n={p['n']})")
  hist = s["queue_wait_hist_ms"]
  if hist:
    lines.append("")
    lines.append("queue wait histogram:")
    peak = max(n for _, n in hist) or 1
    for bound, n in hist:
      bar = "#" * round(40 * n / peak)
      lines.append(f"  <= {bound:>9.3f} ms  {n:>4}  {bar}")
  return "\n".join(lines)


def MergedReport(traces: dict) -> str:
  """Per-replica latency table over {label: trace dict} + a fleet row.

  Each row is that replica's Summary(); the fleet row recomputes the
  percentiles over the UNION of all requests (percentiles don't merge
  from per-replica percentiles)."""
  header = (f"{'replica':<24} {'reqs':>5} {'tokens':>7} "
            f"{'ttft_p50':>9} {'ttft_p99':>9} {'tpot_p50':>9} "
            f"{'tpot_p99':>9} {'total_p50':>10} {'total_p99':>10}")
  lines = [header, "-" * len(header)]

  def _Row(label, reqs):
    ttft = _Percentiles([r.get("ttft_s") for r in reqs])
    tpot = _Percentiles([r.get("tpot_s") for r in reqs])
    total = _Percentiles([r.get("total_s") for r in reqs])

    def _P(p, k):
      return f"{p[k]:.2f}" if p.get("n") else "-"

    return (f"{label:<24} {len(reqs):>5} "
            f"{sum(r.get('tokens', 0) for r in reqs):>7} "
            f"{_P(ttft, 'p50_ms'):>9} {_P(ttft, 'p99_ms'):>9} "
            f"{_P(tpot, 'p50_ms'):>9} {_P(tpot, 'p99_ms'):>9} "
            f"{_P(total, 'p50_ms'):>10} {_P(total, 'p99_ms'):>10}")

  union = []
  for label in sorted(traces):
    reqs = list(traces[label]["perRequest"].values())
    union.extend(reqs)
    lines.append(_Row(label, reqs))
  lines.append("-" * len(header))
  lines.append(_Row("FLEET", union))
  lines.append("")
  lines.append("(latencies in ms; fleet percentiles computed over the "
               "union of requests)")
  return "\n".join(lines)


def main(argv=None) -> int:
  argv = sys.argv[1:] if argv is None else argv
  if not argv:
    print(__doc__, file=sys.stderr)
    return 2
  if len(argv) == 1:
    print(Report(LoadTrace(argv[0])))
    return 0
  print(MergedReport({path: LoadTrace(path) for path in argv}))
  return 0


if __name__ == "__main__":
  sys.exit(main())
