#!/usr/bin/env python
"""Convert a raw KITTI object-detection tree to JSONL scene records.

The file-format bridge for `models/car/kitti_input.KittiSceneInputGenerator`
(ref `lingvo/tasks/car/tools/kitti_exporter.py`, which writes TFRecords of
TF Examples — here the target is the framework's JSON-line scene format,
one object per line:
  {"points": [[x, y, z, reflectance], ...],
   "labels": ["Car 0.00 0 ...", ...],
   "calib": {"R0_rect": [...9], "Tr_velo_to_cam": [...12]}}).

Expected input layout (the standard KITTI training split):
  <root>/velodyne/XXXXXX.bin   float32 [N, 4] point clouds
  <root>/label_2/XXXXXX.txt    label lines (absent for test splits)
  <root>/calib/XXXXXX.txt      "KEY: v v v ..." calibration lines

Usage:
  kitti_to_jsonl.py --root=/data/kitti/training --output=train.jsonl \
      [--max_points=120000] [--shards=8]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

import numpy as np


def ReadVelodyne(path: str, max_points: int = 0) -> np.ndarray:
  pts = np.fromfile(path, dtype=np.float32).reshape(-1, 4)
  if max_points and len(pts) > max_points:
    idx = np.random.RandomState(0).choice(len(pts), max_points,
                                          replace=False)
    pts = pts[np.sort(idx)]
  return pts


def ReadCalib(path: str) -> dict:
  """KITTI calib file -> the two matrices the scene format carries."""
  out = {}
  with open(path) as f:
    for line in f:
      if ":" not in line:
        continue
      key, vals = line.split(":", 1)
      key = key.strip()
      if key in ("R0_rect", "Tr_velo_to_cam"):
        out[key] = [float(v) for v in vals.split()]
  return out


def SceneRecord(velo_path: str, label_path: str | None,
                calib_path: str | None, max_points: int) -> dict:
  # float64 before round: float32 values re-expand to ~17-digit doubles
  # in JSON, tripling the output size the rounding was meant to shrink
  rec = {"points": ReadVelodyne(
      velo_path, max_points).astype(np.float64).round(4).tolist()}
  if label_path and os.path.exists(label_path):
    with open(label_path) as f:
      rec["labels"] = [ln.strip() for ln in f if ln.strip()]
  if calib_path and os.path.exists(calib_path):
    calib = ReadCalib(calib_path)
    # both matrices or none: a partial calib would crash the consumer's
    # camera->velo transform instead of falling back to the nominal one
    if set(calib) == {"R0_rect", "Tr_velo_to_cam"}:
      rec["calib"] = calib
  return rec


def Convert(root: str, output: str, max_points: int = 0,
            shards: int = 1) -> int:
  velos = sorted(glob.glob(os.path.join(root, "velodyne", "*.bin")))
  if not velos:
    raise FileNotFoundError(f"no velodyne/*.bin under {root}")
  outs = []
  if shards <= 1:
    outs = [open(output, "w")]
  else:
    outs = [open(f"{output}-{i:05d}-of-{shards:05d}", "w")
            for i in range(shards)]
  n = 0
  try:
    for velo in velos:
      stem = os.path.splitext(os.path.basename(velo))[0]
      rec = SceneRecord(
          velo,
          os.path.join(root, "label_2", f"{stem}.txt"),
          os.path.join(root, "calib", f"{stem}.txt"),
          max_points)
      outs[n % len(outs)].write(json.dumps(rec) + "\n")
      n += 1
  finally:
    for f in outs:
      f.close()
  return n


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--root", required=True,
                  help="KITTI split dir containing velodyne/ label_2/ calib/")
  ap.add_argument("--output", required=True,
                  help="Output JSONL path (sharded suffixes when --shards>1).")
  ap.add_argument("--max_points", type=int, default=0,
                  help="Subsample clouds beyond this many points (0 = keep).")
  ap.add_argument("--shards", type=int, default=1)
  args = ap.parse_args(argv)
  n = Convert(args.root, args.output, args.max_points, args.shards)
  print(f"wrote {n} scenes")
  return 0


if __name__ == "__main__":
  sys.exit(main())
