#!/usr/bin/env python
"""Real-corpus MT convergence run (VERDICT r4 Next #3): trains
`mt.wmt14_en_de.WmtEnDeRealShardSmall` on the reference's shipped real
WMT'14 wordpiece shard and records the loss + held-out token-BLEU
trajectory into BASELINE.md.

Steps:
  1. prep: tools/t2t_to_jsonl.py on the reference shard -> train/dev split
     under $LINGVO_TPU_DATA_DIR/wmt14_real/ (8,441 train / 500 dev pairs)
  2. train with the production TrainStep, logging loss every --log_every
  3. every --eval_every steps: greedy-decode dev batches, corpus token BLEU
  4. append the trajectory (JSONL + BASELINE.md block)

Usage: python tools/wmt_convergence.py [--steps=3000] [--eval_every=500]
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

REF_SHARD = ("/root/reference/lingvo/tasks/mt/testdata/"
             "translate_ende_wmt32k-train-00511-of-00512")
DEV_N = 500


def PrepareData(data_dir: str) -> None:
  out_dir = os.path.join(data_dir, "wmt14_real")
  train, dev = (os.path.join(out_dir, f) for f in
                ("train.jsonl", "dev.jsonl"))
  if os.path.exists(train) and os.path.exists(dev):
    return
  os.makedirs(out_dir, exist_ok=True)
  allf = os.path.join(out_dir, "all.jsonl")
  tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "t2t_to_jsonl.py")
  subprocess.run([sys.executable, tool, REF_SHARD, allf], check=True)
  lines = open(allf).read().splitlines()
  # deterministic split: last DEV_N lines held out
  with open(train, "w") as f:
    f.write("\n".join(lines[:-DEV_N]) + "\n")
  with open(dev, "w") as f:
    f.write("\n".join(lines[-DEV_N:]) + "\n")
  os.remove(allf)
  print(f"prepared {len(lines) - DEV_N} train / {DEV_N} dev pairs",
        file=sys.stderr)


def Main():
  opts = dict(a[2:].split("=", 1) if "=" in a else (a[2:], "1")
              for a in sys.argv[1:] if a.startswith("--"))
  steps = int(opts.get("steps", 3000))
  log_every = int(opts.get("log_every", 25))
  eval_every = int(opts.get("eval_every", 500))
  data_dir = os.environ.setdefault("LINGVO_TPU_DATA_DIR",
                                   "/tmp/lingvo_tpu_data")
  PrepareData(data_dir)

  import jax
  import jax.numpy as jnp
  import numpy as np
  from lingvo_tpu import model_registry
  from lingvo_tpu.core import input_policy, metrics as metrics_lib
  import lingvo_tpu.models.all_params  # noqa: F401

  mp = model_registry.GetParams("mt.wmt14_en_de.WmtEnDeRealShardSmall",
                                "Train")
  mp.task.input = mp.input
  task = mp.task.Instantiate()
  task.FinalizePaths()
  state = task.CreateTrainState(jax.random.PRNGKey(0))
  gen = input_policy.Instantiate(mp.input)
  step_fn = jax.jit(task.TrainStep, donate_argnums=(0,))

  dev_p = model_registry.GetParams("mt.wmt14_en_de.WmtEnDeRealShardSmall",
                                   "Dev")

  def DevBleu(theta, max_batches=6):
    dgen = input_policy.Instantiate(dev_p.input)
    metric = metrics_lib.CorpusBleuMetric()
    decode = jax.jit(task.Decode)
    n = 0
    for batch in (dgen.EpochBatches() if hasattr(dgen, "EpochBatches")
                  else iter(lambda: dgen.GetPreprocessedInputBatch(), None)):
      out = task.PostProcessDecodeOut(
          jax.tree_util.tree_map(np.asarray,
                                 decode(theta, batch.Transform(jnp.asarray))),
          {"corpus_bleu": metric, "num_samples_in_batch":
           metrics_lib.AverageMetric()})
      del out
      n += 1
      if n >= max_batches:
        break
    return float(metric.value)

  log_path = os.path.join(os.path.dirname(os.path.dirname(
      os.path.abspath(__file__))) if "repo" not in os.getcwd()
      else os.getcwd(), "WMT_CONVERGENCE.jsonl")
  log_path = os.path.abspath(os.path.join(
      os.path.dirname(os.path.abspath(__file__)), "..",
      "WMT_CONVERGENCE.jsonl"))
  t0 = time.time()
  traj = []
  with open(log_path, "a") as logf:
    for step in range(1, steps + 1):
      batch = gen.GetPreprocessedInputBatch().Transform(jnp.asarray)
      state, out = step_fn(state, batch)
      if step % log_every == 0 or step == 1:
        loss = float(out.metrics.loss[0])
        row = {"step": step, "loss": round(loss, 4),
               "wall_s": round(time.time() - t0, 1)}
        if step % eval_every == 0 or step == steps:
          row["dev_token_bleu"] = round(DevBleu(state.theta), 4)
        traj.append(row)
        logf.write(json.dumps(row) + "\n")
        logf.flush()
        print(json.dumps(row), file=sys.stderr)
  print(json.dumps({"trajectory": traj[-8:]}))


if __name__ == "__main__":
  Main()
