#!/usr/bin/env python
"""Librispeech-style featurization: wav audio -> log-mel recordio shards
the ASR input pipeline can read (ref `lingvo/tools/create_asr_features.py`
+ `audio_lib.py`).

Uses the framework's own MelAsrFrontend (the same op the model applies to
raw waveform at training time) so offline features == online features.
Input manifest: lines of "<audio_path>\t<transcript>"."""

from __future__ import annotations

import argparse
import struct
import sys
import wave

import numpy as np


def _ReadWav(path: str) -> tuple[np.ndarray, int]:
  with wave.open(path, "rb") as w:
    rate = w.getframerate()
    n = w.getnframes()
    raw = w.readframes(n)
    width = w.getsampwidth()
    if width == 2:
      pcm = np.frombuffer(raw, np.int16).astype(np.float32) / 32768.0
    elif width == 4:
      pcm = np.frombuffer(raw, np.int32).astype(np.float32) / 2**31
    else:
      raise ValueError(f"unsupported sample width {width} in {path}")
    if w.getnchannels() > 1:
      pcm = pcm.reshape(-1, w.getnchannels()).mean(-1)
  return pcm, rate


def _WriteRecordio(path: str, records: list[bytes]):
  """Length-prefixed container the native RecordIOIterator reads."""
  with open(path, "wb") as f:
    for rec in records:
      f.write(struct.pack("<I", len(rec)))
      f.write(rec)


def main(argv=None):
  ap = argparse.ArgumentParser(description=__doc__)
  ap.add_argument("--manifest", required=True,
                  help="Lines of '<wav_path>\\t<transcript>'.")
  ap.add_argument("--output", required=True, help="recordio shard path.")
  ap.add_argument("--num_bins", type=int, default=80)
  args = ap.parse_args(argv)

  import jax.numpy as jnp
  from lingvo_tpu.models.asr import frontend as frontend_lib
  from lingvo_tpu.core.nested_map import NestedMap
  import json

  frontends = {}  # sample_rate -> frontend (filterbank depends on the rate)

  records = []
  for line in open(args.manifest):
    line = line.strip()
    if not line:
      continue
    path, transcript = line.split("\t", 1)
    pcm, rate = _ReadWav(path)
    if rate not in frontends:
      frontends[rate] = frontend_lib.MelAsrFrontend.Params().Set(
          num_bins=args.num_bins, sample_rate=rate,
          # filters above Nyquist would be identically zero (8 kHz audio)
          upper_edge_hz=min(7600.0, rate / 2.0)).Instantiate()
    fe = frontends[rate]
    feats, paddings = fe.FProp(NestedMap(), jnp.asarray(pcm[None]), None)
    n = int((1.0 - np.asarray(paddings)[0]).sum()) if paddings is not None \
        else feats.shape[1]
    rec = {
        "features": np.asarray(feats[0, :n]).tolist(),
        "transcript": transcript,
        "sample_rate": rate,
    }
    records.append(json.dumps(rec).encode())
  _WriteRecordio(args.output, records)
  print(f"wrote {len(records)} utterances -> {args.output}")
  return 0


if __name__ == "__main__":
  sys.exit(main())
